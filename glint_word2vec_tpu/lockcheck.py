"""graftrace lock registry + optional runtime lock-discipline instrumentation.

Two concurrency bugs shipped and were caught only by hand review: the PR 9
SIGTERM-handler deadlock (a handler blocked on a plain ``Lock`` already held
by the thread it interrupted) and the PR 12 latency-ring race (sorting a
deque another thread appends to raises ``RuntimeError``). This module is the
RUNTIME half of the machine-check that keeps those classes extinct
(docs/static-analysis.md layer 4; ``tools/graftlint`` R9–R11 is the static
half and parses :data:`LOCK_TABLE` below, so the two halves can never drift
apart).

The registry
------------
Every lock in the tree is constructed through :func:`make_lock` /
:func:`make_rlock` / :func:`make_condition` with a name registered in
:data:`LOCK_TABLE` carrying its owner, its ``kind``, and an ordering
**rank**: a thread may only acquire a lock whose rank is STRICTLY GREATER
than every lock it already holds. Ranks grow from the outermost layers
(data-plane init, serving handles, routers) to the innermost leaves
(telemetry — anything may emit while holding anything else, so the sink is
last). graftlint R9 proves the static acquisition graph respects the ranks;
``GLINT_LOCKCHECK=1`` proves the executed schedules do.

The table is parsed by graftlint as a PURE LITERAL (same contract as the
graftcheck knob registry): no computed keys, no variables — an entry built
by a loop would be invisible to the drift gate, which is a finding, not a
convenience.

Zero cost off
-------------
With checking off (the default) the factories return the raw
``threading.Lock/RLock/Condition`` objects — no wrapper is allocated, no
per-acquisition work exists anywhere (``tools/racecheck.py`` A/Bs this and
``tests/test_racecheck.py`` pins the types). With ``GLINT_LOCKCHECK=1`` (or
:func:`configure`), the factories return checked wrappers that keep a
per-thread held-stack, record the acquisition-order edges actually
executed, flag rank inversions against the static table, count
held-while-blocking windows, and (optionally) perturb the schedule with
seeded yields so racy interleavings stop hiding behind the happy path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# The registry. rank: strictly-increasing acquisition order (outer < inner).
# site: where the construction lives ("path:Qualname" — graftlint R9 fails on
# drift in either direction). kind: lock | rlock | condition; only rlock is
# reentrant, and only rlock-kind locks may appear in a signal handler's call
# closure (R10, the PR 9 contract).
# ---------------------------------------------------------------------------
LOCK_TABLE = {
    "data.native.load": {
        "rank": 10, "kind": "lock",
        "site": "glint_word2vec_tpu/data/native.py:<module>",
        "owner": "one-time ctypes library load (double-checked init)"},
    "data.ingest_native.load": {
        "rank": 11, "kind": "lock",
        "site": "glint_word2vec_tpu/data/ingest_native.py:<module>",
        "owner": "one-time ctypes library load (double-checked init)"},
    "serve.handle": {
        "rank": 20, "kind": "lock",
        "site": "glint_word2vec_tpu/serve/reload.py:ServingHandle.__init__",
        "owner": "atomic (model, index) swap + lease counts (serve/reload.py)"},
    "fleet.router": {
        "rank": 30, "kind": "lock",
        "site": "glint_word2vec_tpu/serve/fleet.py:FleetRouter.__init__",
        "owner": "router counters / rr cursor / latency ring (serve/fleet.py)"},
    "fleet.breaker": {
        "rank": 40, "kind": "lock",
        "site": "glint_word2vec_tpu/serve/fleet.py:CircuitBreaker.__init__",
        "owner": "per-replica breaker state machine (serve/fleet.py)"},
    "fleet.replica.pending": {
        "rank": 50, "kind": "lock",
        "site": "glint_word2vec_tpu/serve/fleet.py:SubprocessReplica.__init__",
        "owner": "ticket table: submit/reader/abandon pairing (serve/fleet.py)"},
    "fleet.replica.write": {
        "rank": 51, "kind": "lock",
        "site": "glint_word2vec_tpu/serve/fleet.py:SubprocessReplica.__init__",
        "owner": "replica stdin: one request line at a time (serve/fleet.py)"},
    "serve.batcher.cv": {
        "rank": 60, "kind": "condition",
        "site": "glint_word2vec_tpu/serve/batcher.py:BatchingScheduler.__init__",
        "owner": "admission queue + counters + latency ring; NON-reentrant — "
                 "the PR 9 dump contract (service.dump_blackbox "
                 "include_stats=False) exists because of this lock"},
    "obs.slo": {
        "rank": 70, "kind": "lock",
        "site": "glint_word2vec_tpu/obs/slo.py:SloTracker.__init__",
        "owner": "SLO window counters (obs/slo.py)"},
    "obs.phases": {
        "rank": 80, "kind": "rlock",
        "site": "glint_word2vec_tpu/obs/phases.py:PhaseAccumulator.__init__",
        "owner": "phase time accounting; reentrant for the handler dump path"},
    "obs.spans": {
        "rank": 81, "kind": "rlock",
        "site": "glint_word2vec_tpu/obs/spans.py:Tracer.__init__",
        "owner": "span ring; reentrant for the handler dump path"},
    "obs.blackbox": {
        "rank": 85, "kind": "rlock",
        "site": "glint_word2vec_tpu/obs/blackbox.py:FlightRecorder.__init__",
        "owner": "flight-recorder rings; reentrant — the PR 9 fix itself"},
    "obs.sink": {
        "rank": 90, "kind": "rlock",
        "site": "glint_word2vec_tpu/obs/sink.py:TelemetrySink.__init__",
        "owner": "telemetry JSONL writer; innermost — any layer may emit "
                 "while holding its own lock; reentrant for handler dumps"},
    "tools.servebench.tickets": {
        "rank": 95, "kind": "lock",
        "site": "tools/servebench.py:offered_load",
        "owner": "servebench client-side latency collection"},
}


class _State:
    """Process-wide checking state. Constructed ONCE at import; the enabled
    flag is read at FACTORY time (lock construction), so enabling after a
    subsystem built its locks instruments only what is built afterwards —
    racecheck builds the whole serving stack after configure()."""

    def __init__(self) -> None:
        self.enabled = os.environ.get("GLINT_LOCKCHECK", "") == "1"
        self.perturb = float(os.environ.get("GLINT_LOCKCHECK_PERTURB", "0.0"))
        self.seed = int(os.environ.get("GLINT_LOCKCHECK_SEED", "0"))
        self.wrappers_allocated = 0
        self.acquisitions = 0
        self.yields = 0
        self.held_while_blocking = 0
        # dedup'd findings/edges, guarded by the (raw, unregistered —
        # bookkeeping, not product) recorder lock below
        self.inversions: Dict[tuple, dict] = {}
        self.edges: Dict[tuple, int] = {}
        self.hwb_pairs: Dict[tuple, int] = {}
        self.thread_seq = 0


_STATE = _State()
_REC_LOCK = threading.Lock()  # bookkeeping only; never visible to the rules
_TLS = threading.local()


def configure(enabled: Optional[bool] = None, seed: Optional[int] = None,
              perturb: Optional[float] = None) -> None:
    """Set checking state programmatically (racecheck/tests). ``enabled``
    applies to locks constructed AFTER the call."""
    if enabled is not None:
        _STATE.enabled = bool(enabled)
    if seed is not None:
        _STATE.seed = int(seed)
    if perturb is not None:
        _STATE.perturb = float(perturb)


def enabled() -> bool:
    return _STATE.enabled


def wrappers_allocated() -> int:
    return _STATE.wrappers_allocated


def reset() -> None:
    """Drop collected events/counters (keeps the enabled/seed/perturb knobs)."""
    with _REC_LOCK:
        _STATE.acquisitions = 0
        _STATE.yields = 0
        _STATE.held_while_blocking = 0
        _STATE.inversions.clear()
        _STATE.edges.clear()
        _STATE.hwb_pairs.clear()


def report() -> dict:
    """The collected evidence: every executed acquisition-order edge, every
    rank inversion, the held-while-blocking windows, and the perturber's
    yield count — the shape racecheck embeds in its JSON line."""
    with _REC_LOCK:
        return {
            "enabled": _STATE.enabled,
            "wrappers_allocated": _STATE.wrappers_allocated,
            "acquisitions": _STATE.acquisitions,
            "perturb_yields": _STATE.yields,
            "held_while_blocking": _STATE.held_while_blocking,
            "held_while_blocking_pairs": sorted(
                f"{a}->{b}" for a, b in _STATE.hwb_pairs),
            "edges": sorted(f"{a}->{b}" for a, b in _STATE.edges),
            "inversions": [dict(v) for _, v in sorted(
                _STATE.inversions.items())],
        }


def _held() -> List["_Held"]:
    try:
        return _TLS.held
    except AttributeError:
        _TLS.held = []
        return _TLS.held


class _Held:
    __slots__ = ("name", "rank", "obj", "depth")

    def __init__(self, name: str, rank: int, obj: Any) -> None:
        self.name = name
        self.rank = rank
        self.obj = obj
        self.depth = 1


def _maybe_yield() -> None:
    """Seeded schedule perturbation: a sub-millisecond sleep with probability
    ``perturb`` at every instrumented acquire/release — the deterministic
    analog of a scheduler running the OTHER thread first. Per-thread seeded
    generators (R2: np.random.default_rng only) so the schedule is
    reproducible given (seed, thread creation order)."""
    if _STATE.perturb <= 0.0:
        return
    rng = getattr(_TLS, "rng", None)
    if rng is None:
        import numpy as np
        with _REC_LOCK:
            _STATE.thread_seq += 1
            stream = _STATE.thread_seq
        rng = _TLS.rng = np.random.default_rng((int(_STATE.seed), stream))
    if rng.random() < _STATE.perturb:
        with _REC_LOCK:
            _STATE.yields += 1
        time.sleep(float(rng.random()) * 5e-4)


def _record_acquire(entry: "_Checked", blocking_contended: bool) -> None:
    held = _held()
    with _REC_LOCK:
        _STATE.acquisitions += 1
        if held:
            top = held[-1]
            if top.obj is not entry.lock:  # reentrant re-acquire: no edge
                _STATE.edges[(top.name, entry.name)] = (
                    _STATE.edges.get((top.name, entry.name), 0) + 1)
            if blocking_contended:
                _STATE.held_while_blocking += 1
                _STATE.hwb_pairs[(top.name, entry.name)] = (
                    _STATE.hwb_pairs.get((top.name, entry.name), 0) + 1)
            for h in held:
                if h.obj is not entry.lock and h.rank >= entry.rank:
                    key = (h.name, entry.name)
                    _STATE.inversions.setdefault(key, {
                        "kind": "rank-inversion",
                        "held": h.name, "held_rank": h.rank,
                        "acquiring": entry.name, "rank": entry.rank,
                        "thread": threading.current_thread().name})
                elif (h.obj is entry.lock and entry.kind != "rlock"):
                    key = (entry.name, entry.name)
                    _STATE.inversions.setdefault(key, {
                        "kind": "reentrant-nonreentrant",
                        "held": h.name, "held_rank": h.rank,
                        "acquiring": entry.name, "rank": entry.rank,
                        "thread": threading.current_thread().name})


class _Checked:
    """Instrumented lock/rlock wrapper: same acquire/release/context surface
    as the raw primitive, plus held-stack + rank bookkeeping."""

    __slots__ = ("name", "rank", "kind", "lock")

    def __init__(self, name: str, rank: int, kind: str, lock: Any) -> None:
        self.name = name
        self.rank = rank
        self.kind = kind
        self.lock = lock
        with _REC_LOCK:
            _STATE.wrappers_allocated += 1

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _maybe_yield()
        held = _held()
        reentrant = (self.kind == "rlock"
                     and any(h.obj is self.lock for h in held))
        contended = False
        got = self.lock.acquire(False)
        if not got:
            if not blocking:
                # a failed try-lock cannot deadlock: count nothing
                return False
            contended = bool(held)
            got = (self.lock.acquire(True, timeout) if timeout != -1
                   else self.lock.acquire(True))
        if got:
            _record_acquire(self, contended)
        if got:
            if reentrant:
                for h in reversed(held):
                    if h.obj is self.lock:
                        h.depth += 1
                        break
            else:
                held.append(_Held(self.name, self.rank, self.lock))
        return got

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].obj is self.lock:
                held[i].depth -= 1
                if held[i].depth == 0:
                    del held[i]
                break
        self.lock.release()
        _maybe_yield()

    def __enter__(self) -> "_Checked":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self.lock.locked()


class _CheckedCondition(_Checked):
    """Instrumented Condition: acquire/release via the checked protocol;
    ``wait`` drops the held-stack entry for its duration (the lock really is
    released) and counts as a held-while-blocking window when OTHER locks
    stay held across it — exactly the shape that starves a notifier."""

    __slots__ = ("cond",)

    def __init__(self, name: str, rank: int) -> None:
        cond = threading.Condition()
        super().__init__(name, rank, "condition", cond)
        self.cond = cond

    def wait(self, timeout: Optional[float] = None) -> bool:
        held = _held()
        entry = None
        for i in range(len(held) - 1, -1, -1):
            if held[i].obj is self.lock:
                entry = held.pop(i)
                break
        if held:  # waiting while still holding something else
            with _REC_LOCK:
                _STATE.held_while_blocking += 1
                key = (held[-1].name, self.name)
                _STATE.hwb_pairs[key] = _STATE.hwb_pairs.get(key, 0) + 1
        try:
            return self.cond.wait(timeout)
        finally:
            if entry is not None:
                held.append(entry)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        end = None if timeout is None else time.monotonic() + timeout
        result = predicate()
        while not result:
            remaining = None if end is None else end - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self.cond.notify(n)

    def notify_all(self) -> None:
        self.cond.notify_all()


def _entry(name: str, kind: str) -> dict:
    e = LOCK_TABLE.get(name)
    if e is None:
        raise KeyError(
            f"lock {name!r} is not in lockcheck.LOCK_TABLE — register it "
            f"with an owner and a rank (docs/static-analysis.md layer 4)")
    if e["kind"] != kind:
        raise ValueError(
            f"lock {name!r} registered as kind {e['kind']!r} but "
            f"constructed as {kind!r}")
    return e


def make_lock(name: str):
    """A ``threading.Lock`` registered as ``name``. Off: the raw primitive
    (zero wrappers); on: the checked wrapper."""
    if not _STATE.enabled:
        return threading.Lock()
    e = _entry(name, "lock")
    return _Checked(name, e["rank"], "lock", threading.Lock())


def make_rlock(name: str):
    """A ``threading.RLock`` registered as ``name`` (see :func:`make_lock`)."""
    if not _STATE.enabled:
        return threading.RLock()
    e = _entry(name, "rlock")
    return _Checked(name, e["rank"], "rlock", threading.RLock())


def make_condition(name: str):
    """A ``threading.Condition`` registered as ``name`` (see
    :func:`make_lock`)."""
    if not _STATE.enabled:
        return threading.Condition()
    e = _entry(name, "condition")
    return _CheckedCondition(name, e["rank"])
