"""R5 bad: bare data-plane reads (open + memmap) outside retry_io."""
import numpy as np


def load(path):
    with open(path, "r", encoding="utf-8") as f:
        meta = f.read()
    tokens = np.memmap(path + ".bin", dtype=np.int32, mode="r")
    return meta, tokens
