"""Shared banded-CBOW measurement harness: feed packing + chunk builder.

One owner for the wiring that `bench.py` (bench_cbow_banded_step) and
`tools/step_ab.py` (run_cbow_ab) both need — the halo-block → device-feed
packing (incl. the uint64 → 2×int32 ordinal-base split the trainer feed uses)
and the trainer-shaped jitted chunk (scan + hash-PRNG negatives +
device-derived windows + metrics-elided banded step). The two tools are the
PERF.md §9 evidence chain for the same number; sharing the wiring means a
future feed-contract change (obase packing, CbowBand fields) cannot make them
disagree for harness reasons.
"""

from __future__ import annotations

import numpy as np


def pack_banded_feeds(toks: np.ndarray, starts: np.ndarray, T: int, halo: int,
                      n_sets: int, K: int) -> list:
    """Cut a kept-token stream into ``n_sets`` device-feed dicts of K halo
    blocks each ({tokens [K,T], starts, nvalid [K], obase [K,2] int32}) — the
    single-segment shape of the trainer's banded chunk feed. The stream must
    supply at least n_sets·K blocks (callers size it; StopIteration otherwise
    is a sizing bug, not a harness feature)."""
    import jax.numpy as jnp

    from glint_word2vec_tpu.data.pipeline import pack_halo_token_blocks

    blocks = pack_halo_token_blocks([(toks, starts)], T, halo, np.int32)
    feeds = []
    for _ in range(n_sets):
        rows = [next(blocks) for _ in range(K)]
        feeds.append({
            "tokens": jnp.asarray(np.stack([r[0] for r in rows]), jnp.int32),
            "starts": jnp.asarray(np.stack([r[1] for r in rows])),
            "nvalid": jnp.asarray([r[2] for r in rows], jnp.int32),
            "obase": jnp.asarray(np.asarray(
                [[r[3] & 0xFFFFFFFF, r[3] >> 32] for r in rows],
                np.uint32).view(np.int32)),
        })
    return feeds


def make_banded_chunk(window: int, pool: int, num_negatives: int,
                      compute_dtype, logits_dtype, win_base: int, K: int,
                      seed: int = 1234):
    """The trainer-shaped banded chunk: scan K steps, negatives from the hash
    PRNG, per-slot windows derived on device, metrics elided (the production
    steady state both measurement tools time). Returns a plain function for
    the caller to jit (donate_argnums=(0,)): f(params, feed, base_step, prob,
    alias) -> (params, losses)."""
    import jax
    import jax.numpy as jnp

    from glint_word2vec_tpu.ops.cbow_banded import cbow_step_banded_core
    from glint_word2vec_tpu.ops.pairgen import device_cbow_windows
    from glint_word2vec_tpu.ops.sampler import sample_negatives_hash

    wb = jnp.uint32(win_base)

    def chunk(params, feed, base_step, prob, alias):
        negs = sample_negatives_hash(prob, alias, seed, base_step, (K, pool))

        def body(p, inp):
            tb, bits, nv, ob, ng = inp
            obu = jax.lax.bitcast_convert_type(ob, jnp.uint32)
            band = device_cbow_windows(tb, bits, nv, obu[0], obu[1], wb,
                                       window=window, halo=window)
            new_p, m = cbow_step_banded_core(
                p, tb, band.left, band.right, band.center, band.token,
                ng, jnp.float32(0.025), num_negatives, window, "exact",
                compute_dtype, logits_dtype, with_metrics=False)
            return new_p, m.loss

        return jax.lax.scan(body, params, (
            feed["tokens"], feed["starts"], feed["nvalid"], feed["obase"],
            negs))

    return chunk
