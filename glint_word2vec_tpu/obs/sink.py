"""Structured telemetry sink: a schema-versioned, rotating JSONL run log.

The reference's only run artifact is the every-10k-words log LINE
(``wordCount/alpha/fPlus`` through the Spark driver's logger, mllib:411-412)
— unparseable, unbounded, and gone when the driver log rotates. This sink
persists the extended heartbeats (norm channels, per-phase host timings)
plus run-start/run-end records as one machine-readable file per run.

Contract:

- every record is one JSON line validating against :mod:`.schema` (the CI
  drift gate);
- records go to a FILE, never stdout — the driver tools' exactly-one-JSON-
  line stdout contract (graftlint R7) must survive a trainer with telemetry
  on running inside any of them;
- rotation: when the active file exceeds ``rotate_bytes`` it is renamed to
  ``<path>.1`` (shifting older segments up, oldest dropped past ``keep``),
  so a long run's telemetry is bounded like the heartbeat ring it mirrors;
- thread-safe: the producer/stager threads emit span summaries and the main
  thread emits heartbeats — one lock serializes writes (a JSON line is a
  single ``write()`` call, so segments never interleave mid-line).

Writes are best-effort by design: telemetry must never kill a training run,
so I/O errors are logged once and the sink disables itself (the run log is
an observability artifact, not training state — the checkpoint layer owns
durability).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

from glint_word2vec_tpu.obs.schema import SCHEMA_VERSION
from glint_word2vec_tpu.lockcheck import make_rlock

logger = logging.getLogger("glint_word2vec_tpu")


class TelemetrySink:
    """Append-only rotating JSONL writer for one run log path."""

    def __init__(self, path: str, rotate_bytes: int = 64 << 20,
                 keep: int = 3):
        if keep < 1:
            raise ValueError(f"keep must be >= 1 but got {keep}")
        self.path = path
        self.rotate_bytes = int(rotate_bytes)
        self.keep = int(keep)
        # RLock: the flight recorder's SIGTERM path emits the run_end
        # record from the main-thread signal handler — a plain Lock held by
        # that same thread's interrupted emit() would deadlock the handler
        # (obs/blackbox.py has the full rationale)
        self._lock = make_rlock("obs.sink")
        self._file = None
        self._size = 0
        self._dead = False
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    # -- writing ----------------------------------------------------------------

    @classmethod
    def _sanitize(cls, v):
        """Strict-JSON guard: json.dumps' default emits bare ``NaN``/
        ``Infinity`` tokens (RFC-8259-invalid; jq and most non-Python parsers
        reject the line) — and non-finite values show up exactly in the
        diverging runs telemetry exists to diagnose. Non-finite floats become
        null (the schema admits null for numeric fields for this reason);
        same rule class as eval_quality's strict-JSON EVAL_RUNS clamp."""
        if isinstance(v, float):
            return v if v == v and abs(v) != float("inf") else None
        if isinstance(v, dict):
            return {k: cls._sanitize(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [cls._sanitize(x) for x in v]
        return v

    def emit(self, kind: str, **fields) -> None:
        """Write one schema-stamped record. Never raises (see module doc)."""
        rec = {"schema": SCHEMA_VERSION, "kind": kind,
               "t": round(time.time(), 3), **self._sanitize(fields)}
        try:
            line = json.dumps(rec, allow_nan=False) + "\n"
        except (TypeError, ValueError) as e:
            logger.warning("telemetry record dropped (unserializable %s "
                           "record: %s)", kind, e)
            return
        with self._lock:
            if self._dead:
                return
            try:
                if self._file is None:
                    self._open()
                if self._size + len(line) > self.rotate_bytes and self._size:
                    self._rotate()
                self._file.write(line)
                self._file.flush()
                self._size += len(line)
            except OSError as e:
                self._dead = True
                logger.warning(
                    "telemetry sink disabled after write failure on %s: %s "
                    "(training continues; the run log is best-effort)",
                    self.path, e)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    # -- internals (lock held) --------------------------------------------------

    def _open(self) -> None:
        self._file = open(self.path, "a", encoding="utf-8")
        self._size = self._file.tell()

    def _rotate(self) -> None:
        self._file.close()
        self._file = None
        # shift <path> -> <path>.1 -> ... -> <path>.keep; the oldest falls off
        # (os.replace overwrites), so disk usage is bounded by
        # (keep + 1) * rotate_bytes per run log
        for i in range(self.keep, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i}")
        self._open()

    def __enter__(self) -> "TelemetrySink":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.close()
        return None
