"""R3 good: pure device math inside the jitted step; host fetches outside."""
import jax
import jax.numpy as jnp


def step(params, batch):
    scale = jnp.mean(batch)
    return params * scale


step_fn = jax.jit(step)


def heartbeat(metrics):
    return float(metrics[-1])  # host fetch OUTSIDE the jitted program: fine
