"""Full-step A/B: current 3-scatter shared-pool SGNS step vs merged-scatter variants.

HLO analysis (tools/scatter_model.py + compiled-HLO dump) shows each scatter-add pays
a fixed cost — index sort + a [B,D] update permute + a serial sorted-scatter emitter
(~27 ns/row) — and the production step pays it three times (syn0[centers],
syn1[contexts], syn1[pool]). Variants measured here, all mathematically identical to
sgns_step_shared_core (scatter-add is order-independent up to FP associativity):

    current     — sgns_step_shared_core as shipped (3 scatters)
    merged-syn1 — contexts+pool in one scatter (2 scatters)
    merged-all  — one [2V,D] array, centers/contexts/pool in ONE scatter
    merged-all + dense head H — rows < H updated via one-hot matmul (MXU) and a
                  dense slab add; only tail rows scattered. Exact (one-hot of a
                  head row is zero for tail ids), no compaction needed for A/B —
                  scatter still processes B rows but the cost model says rows are
                  what matters, so this row only shows matmul overhead vs scatter
                  savings potential with compaction.

Run: python tools/step_ab.py [--dtype f32|bf16] [--b 65536] [--pool 256]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V, D, NEG, K = 200_000, 384, 5, 16


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="f32", choices=["f32", "bf16"])
    ap.add_argument("--b", type=int, default=65536)
    ap.add_argument("--pool", type=int, default=256)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()
    B, P = args.b, args.pool

    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    from glint_word2vec_tpu.ops.sampler import build_alias_table, sample_negatives_hash
    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, _log_sigmoid, _sigmoid, init_embeddings,
        sgns_step_shared_core)

    dt = jnp.float32 if args.dtype == "f32" else jnp.bfloat16
    print(f"device: {jax.devices()[0]}  dtype={args.dtype} B={B} pool={P}",
          file=sys.stderr)

    rng = np.random.default_rng(0)
    counts = np.maximum(1e9 / (np.arange(V) + 10.0) ** 1.07, 5.0)
    p = counts / counts.sum()
    table = build_alias_table(counts)
    prob, alias = table.prob, table.alias
    syn0_0 = init_embeddings(V, D, jax.random.key(0)).syn0.astype(dt)
    syn1_0 = jnp.asarray(rng.normal(0, 0.05, (V, D)), dt)

    batches = []
    for i in range(12):
        r = np.random.default_rng(1000 + i)
        batches.append({
            "centers": jnp.asarray(r.choice(V, size=(K, B), p=p), jnp.int32),
            "contexts": jnp.asarray(r.choice(V, size=(K, B), p=p), jnp.int32),
            "mask": jnp.ones((K, B), jnp.float32),
        })

    def core_merged(syn, centers, contexts, mask, negatives, alpha, dense_head=0):
        """One-scatter variant on merged [2V, D] (rows V..2V-1 are syn1)."""
        cdt = jnp.float32
        e_in = syn[centers].astype(cdt)
        e_pos = syn[V + contexts].astype(cdt)
        Z = syn[V + negatives].astype(cdt)
        f_pos = jnp.sum(e_in * e_pos, axis=-1)
        f_neg = e_in @ Z.T
        neg_valid = (negatives[None, :] != contexts[:, None]).astype(cdt) \
            * mask[:, None]
        g_pos = (1.0 - _sigmoid(f_pos, "exact")) * alpha * mask
        g_neg = (0.0 - _sigmoid(f_neg, "exact")) * alpha * neg_valid * (NEG / P)
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        d_pos = g_pos[:, None] * e_in
        d_Z = g_neg.T @ e_in
        idx = jnp.concatenate([centers, V + contexts, V + negatives])
        upd = jnp.concatenate([d_in, d_pos, d_Z]).astype(syn.dtype)
        if dense_head:
            H = dense_head
            # head rows (idx % V < H) ride the MXU: one-hot matmul -> dense add
            local = jnp.where(idx >= V, idx - V, idx)
            half = (idx >= V).astype(jnp.int32)
            is_head = local < H
            oh = ((local[:, None] == jnp.arange(H)[None, :]) &
                  (half[:, None] == 0)).astype(upd.dtype)
            oh1 = ((local[:, None] == jnp.arange(H)[None, :]) &
                   (half[:, None] == 1)).astype(upd.dtype)
            head0 = oh.T @ upd
            head1 = oh1.T @ upd
            syn = syn.at[:H].add(head0)
            syn = syn.at[V:V + H].add(head1)
            idx = jnp.where(is_head, 2 * V, idx)  # dropped
            syn = syn.at[idx].add(upd, mode="drop")
        else:
            syn = syn.at[idx].add(upd)
        loss = (-_log_sigmoid(f_pos) * mask
                - jnp.sum(_log_sigmoid(-f_neg) * neg_valid, axis=-1)
                * (NEG / P)).sum() / jnp.maximum(mask.sum(), 1.0)
        return syn, loss

    def core_merged_syn1(params, centers, contexts, mask, negatives, alpha):
        """contexts+pool in one scatter; syn0/syn1 stay separate (2 scatters)."""
        syn0, syn1 = params
        cdt = jnp.float32
        e_in = syn0[centers].astype(cdt)
        e_pos = syn1[contexts].astype(cdt)
        Z = syn1[negatives].astype(cdt)
        f_pos = jnp.sum(e_in * e_pos, axis=-1)
        f_neg = e_in @ Z.T
        neg_valid = (negatives[None, :] != contexts[:, None]).astype(cdt) \
            * mask[:, None]
        g_pos = (1.0 - _sigmoid(f_pos, "exact")) * alpha * mask
        g_neg = (0.0 - _sigmoid(f_neg, "exact")) * alpha * neg_valid * (NEG / P)
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        d_pos = g_pos[:, None] * e_in
        d_Z = g_neg.T @ e_in
        new_syn0 = syn0.at[centers].add(d_in.astype(syn0.dtype))
        idx1 = jnp.concatenate([contexts, negatives])
        upd1 = jnp.concatenate([d_pos, d_Z]).astype(syn1.dtype)
        new_syn1 = syn1.at[idx1].add(upd1)
        loss = (-_log_sigmoid(f_pos) * mask
                - jnp.sum(_log_sigmoid(-f_neg) * neg_valid, axis=-1)
                * (NEG / P)).sum() / jnp.maximum(mask.sum(), 1.0)
        return EmbeddingPair(new_syn0, new_syn1), loss

    def make_runner(kind, dense_head=0):
        def chunk(state, batch, base_step, prob, alias):
            negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, P))

            def body(s, inp):
                b, ng = inp
                if kind == "current":
                    new_p, m = sgns_step_shared_core(
                        s, b["centers"], b["contexts"], b["mask"], ng,
                        jnp.float32(0.025), NEG, "exact", jnp.float32)
                    return new_p, m.loss
                if kind == "merged_syn1":
                    return core_merged_syn1(
                        s, b["centers"], b["contexts"], b["mask"], ng,
                        jnp.float32(0.025))
                return core_merged(
                    s, b["centers"], b["contexts"], b["mask"], ng,
                    jnp.float32(0.025), dense_head)
            return jax.lax.scan(body, state, (batch, negs))

        f = jax.jit(chunk, donate_argnums=(0,))

        if kind == "merged":
            def mk():
                return jnp.concatenate([syn0_0, syn1_0])
        else:
            def mk():
                return EmbeddingPair(syn0_0 + 0, syn1_0 + 0)

        def run():
            return time_chunked(
                f, mk, lambda i: (batches[i % 12], np.int32(100 + i), prob, alias),
                n_lo=2, n_hi=8, fetch=lambda c, out: out[-1])
        return run

    # ---- center-grouped variant: the reference's wOutput shape (mllib:419) ----
    # skip-gram emits ~2*window pairs per center; grouping contexts per center
    # cuts syn0 gather+scatter rows and the pool matmul by the group width.
    W = 10                      # 2*window slots
    FILL = 0.655                # mean window fill under the reference's shrink rule
    Bc = max(1, int(B * 1.0 / (W * FILL)))  # groups per batch ~ same real pairs

    gbatches = []
    for i in range(12):
        r = np.random.default_rng(2000 + i)
        centers = np.sort(r.choice(V, size=(K, Bc), p=p), axis=-1)  # host-sorted
        ctx = r.choice(V, size=(K, Bc, W), p=p)
        n_ctx = r.integers(1, W + 1, size=(K, Bc))
        cmask = (np.arange(W)[None, None, :] < n_ctx[..., None])
        gbatches.append({
            "centers": jnp.asarray(centers, jnp.int32),
            "ctx": jnp.asarray(ctx, jnp.int32),
            "cmask": jnp.asarray(cmask, jnp.float32),
        })
    real_pairs = float(np.mean([np.asarray(g["cmask"]).sum(axis=(1, 2)).mean()
                                for g in gbatches]))

    def core_grouped(params, centers, ctx, cmask, negatives, alpha):
        syn0, syn1 = params
        cdt = jnp.float32
        e_in = syn0[centers].astype(cdt)                 # [Bc, D]
        e_pos = syn1[ctx].astype(cdt)                    # [Bc, W, D]
        Z = syn1[negatives].astype(cdt)                  # [P, D]
        f_pos = jnp.einsum("bd,bwd->bw", e_in, e_pos)
        f_neg = e_in @ Z.T                               # [Bc, P] — per center!
        neg_valid = (negatives[None, :] != centers[:, None]).astype(cdt)
        n_ctx = cmask.sum(axis=-1)                       # [Bc]
        g_pos = (1.0 - _sigmoid(f_pos, "exact")) * alpha * cmask
        # per-pair negative term depends only on the center -> weight by n_ctx
        g_neg = ((0.0 - _sigmoid(f_neg, "exact")) * alpha * neg_valid
                 * (NEG / P)) * n_ctx[:, None]
        d_in = jnp.einsum("bw,bwd->bd", g_pos, e_pos) + g_neg @ Z
        d_pos = g_pos[..., None] * e_in[:, None, :]      # [Bc, W, D]
        d_Z = g_neg.T @ e_in                             # [P, D]
        new_syn0 = syn0.at[centers].add(d_in.astype(syn0.dtype),
                                        indices_are_sorted=True)
        new_syn1 = syn1.at[ctx.reshape(-1)].add(
            d_pos.reshape(-1, D).astype(syn1.dtype))
        new_syn1 = new_syn1.at[negatives].add(d_Z.astype(syn1.dtype))
        loss = (f_pos * cmask).sum() / jnp.maximum(cmask.sum(), 1.0)
        return EmbeddingPair(new_syn0, new_syn1), loss

    def make_grouped_runner():
        def chunk(state, batch, base_step, prob, alias):
            negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, P))

            def body(s, inp):
                b, ng = inp
                return core_grouped(s, b["centers"], b["ctx"], b["cmask"], ng,
                                    jnp.float32(0.025))
            return jax.lax.scan(body, state, (batch, negs))

        f = jax.jit(chunk, donate_argnums=(0,))

        def run():
            return time_chunked(
                f, lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
                lambda i: (gbatches[i % 12], np.int32(100 + i), prob, alias),
                n_lo=2, n_hi=8, fetch=lambda c, out: out[-1])
        return run

    # ---- host-sorted batch + indices_are_sorted on the syn0 scatter ----------
    sbatches = []
    for i in range(12):
        b = batches[i]
        c = np.asarray(b["centers"])
        x = np.asarray(b["contexts"])
        order = np.argsort(c, axis=-1)
        sbatches.append({
            "centers": jnp.asarray(np.take_along_axis(c, order, -1), jnp.int32),
            "contexts": jnp.asarray(np.take_along_axis(x, order, -1), jnp.int32),
            "mask": b["mask"],
        })

    def core_sorted(params, centers, contexts, mask, negatives, alpha):
        syn0, syn1 = params
        cdt = jnp.float32
        e_in = syn0[centers].astype(cdt)
        e_pos = syn1[contexts].astype(cdt)
        Z = syn1[negatives].astype(cdt)
        f_pos = jnp.sum(e_in * e_pos, axis=-1)
        f_neg = e_in @ Z.T
        neg_valid = (negatives[None, :] != contexts[:, None]).astype(cdt) \
            * mask[:, None]
        g_pos = (1.0 - _sigmoid(f_pos, "exact")) * alpha * mask
        g_neg = (0.0 - _sigmoid(f_neg, "exact")) * alpha * neg_valid * (NEG / P)
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        d_pos = g_pos[:, None] * e_in
        d_Z = g_neg.T @ e_in
        new_syn0 = syn0.at[centers].add(d_in.astype(syn0.dtype),
                                        indices_are_sorted=True)
        new_syn1 = syn1.at[contexts].add(d_pos.astype(syn1.dtype))
        new_syn1 = new_syn1.at[negatives].add(d_Z.astype(syn1.dtype))
        loss = (-_log_sigmoid(f_pos) * mask
                - jnp.sum(_log_sigmoid(-f_neg) * neg_valid, axis=-1)
                * (NEG / P)).sum() / jnp.maximum(mask.sum(), 1.0)
        return EmbeddingPair(new_syn0, new_syn1), loss

    def make_sorted_runner():
        def chunk(state, batch, base_step, prob, alias):
            negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, P))

            def body(s, inp):
                b, ng = inp
                return core_sorted(s, b["centers"], b["contexts"], b["mask"], ng,
                                   jnp.float32(0.025))
            return jax.lax.scan(body, state, (batch, negs))

        f = jax.jit(chunk, donate_argnums=(0,))

        def run():
            return time_chunked(
                f, lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
                lambda i: (sbatches[i % 12], np.int32(100 + i), prob, alias),
                n_lo=2, n_hi=8, fetch=lambda c, out: out[-1])
        return run

    runners = {
        "current (3 scatters)": make_runner("current"),
        "sorted-centers + flag": make_sorted_runner(),
        "merged-syn1 (2 scatters)": make_runner("merged_syn1"),
        "grouped-centers": make_grouped_runner(),
    }
    times = {k: [] for k in runners}
    for r in range(args.repeats):
        for name, run in runners.items():
            spc = run()
            times[name].append(spc / K * 1e3)
    print(f"\nSGNS step A/B (B={B}, pool={P}, {args.dtype}, median of "
          f"{args.repeats} interleaved repeats):", file=sys.stderr)
    for name, ts in times.items():
        med = float(np.median(ts))
        pairs = real_pairs if name == "grouped-centers" else B
        print(f"  {name:28s} median {med:7.3f} ms/step  "
              f"[{min(ts):7.3f} .. {max(ts):7.3f}]  "
              f"{pairs / (med / 1e3):13,.0f} pairs/s", file=sys.stderr)
    print(f"  (grouped: Bc={Bc} groups x W={W} slots, "
          f"{real_pairs:,.0f} real pairs/step)", file=sys.stderr)


if __name__ == "__main__":
    main()
