"""Numerical-equivalence tests for the fused Pallas SGNS kernel (interpret mode).

The kernel (ops/pallas/sgns_kernel.py) must produce the same update as the XLA
reference implementation ``sgns_step_shared`` (ops/sgns.py) given the same PRNG key,
wherever their concurrency semantics coincide: batches whose centers are distinct
among themselves and whose contexts are distinct among themselves (in-tile duplicates
are last-wins in the kernel vs accumulated by XLA scatter-add — documented divergence,
sgns_kernel.py module docstring).

Replaces-the-reference note: these cover the G3 dotprod + G4 adjust server kernels
(mllib:419-425) at the numerical level the reference never tested (SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glint_word2vec_tpu.ops.pallas.sgns_kernel import make_pallas_sgns_step
from glint_word2vec_tpu.ops.sampler import build_alias_table, sample_negatives
from glint_word2vec_tpu.ops.sgns import (
    EmbeddingPair,
    init_embeddings,
    sgns_step_shared_core,
)

V, D, P, N = 512, 128, 64, 5


# exception classes that mean "this jax/jaxlib/backend cannot build or lower
# the kernel" — a moved API surface (pltpu.CompilerParams ↔ TPUCompilerParams),
# an unimplemented lowering, or an XLA runtime refusal. Anything OUTSIDE this
# set (TypeError from a wiring bug, ValueError from bad shapes, assertion
# failures) propagates and turns the suite red — only true environment
# failures may skip.
_ENV_ERROR_TYPES = [AttributeError, ImportError, NotImplementedError]
try:
    from jaxlib.xla_extension import XlaRuntimeError
    _ENV_ERROR_TYPES.append(XlaRuntimeError)
except ImportError:
    pass


def _pallas_env_error():
    """Probe whether this environment can build AND run the Pallas kernel at
    all (known-good shapes, interpret mode). Pallas's TPU API surface moves
    between jax releases and some backends cannot lower the kernel — those are
    ENVIRONMENT failures, not regressions, so the equivalence tests skip with
    the probe's reason instead of running tier-1 red. Numerical mismatches are
    untouched: the probe never compares values, it only checks the kernel
    executes; non-environment exception classes propagate (see
    _ENV_ERROR_TYPES)."""
    try:
        inner = make_pallas_sgns_step(N, P, "exact", jnp.float32, tile=64,
                                      interpret=True)
        params = EmbeddingPair(jnp.zeros((V, D), jnp.float32),
                               jnp.zeros((V, D), jnp.float32))
        batch = {"centers": jnp.zeros(64, jnp.int32),
                 "contexts": jnp.ones(64, jnp.int32),
                 "mask": jnp.ones(64, jnp.float32)}
        inner(params, batch, jnp.zeros(P, jnp.int32), jnp.float32(0.01))
        return None
    except tuple(_ENV_ERROR_TYPES) as e:
        return f"{type(e).__name__}: {e}"


_PALLAS_ENV_ERROR = _pallas_env_error()
needs_pallas = pytest.mark.skipif(
    _PALLAS_ENV_ERROR is not None,
    reason=("backend cannot lower/run the Pallas SGNS kernel in this "
            f"environment: {_PALLAS_ENV_ERROR}"))


def _setup(seed=0):
    rng = np.random.default_rng(seed)
    counts = rng.integers(1, 50, V)
    table = build_alias_table(counts, 0.75)
    params = init_embeddings(V, D, jax.random.key(1))
    # nonzero syn1 so negative-branch math is exercised
    syn1 = jnp.asarray(rng.normal(0.0, 0.05, (V, D)), jnp.float32)
    return table, EmbeddingPair(params.syn0, syn1), rng


def _distinct_batch(rng, B):
    centers = rng.permutation(V)[:B].astype(np.int32)
    contexts = rng.permutation(V)[:B].astype(np.int32)
    mask = np.ones(B, np.float32)
    return centers, contexts, mask


def _run_both(table, params, centers, contexts, mask, tile, alpha=0.025):
    negatives = sample_negatives(table, jax.random.key(7), (P,))
    batch = {
        "centers": jnp.asarray(centers),
        "contexts": jnp.asarray(contexts),
        "mask": jnp.asarray(mask),
    }
    pallas_inner = make_pallas_sgns_step(
        N, P, "exact", jnp.float32, tile=tile, interpret=True)
    got_params, got_metrics = pallas_inner(
        params, batch, negatives, jnp.float32(alpha))
    want_params, want_metrics = sgns_step_shared_core(
        params, batch["centers"], batch["contexts"], batch["mask"],
        negatives, jnp.float32(alpha), N, "exact", jnp.float32)
    return got_params, got_metrics, want_params, want_metrics


@needs_pallas
def test_single_tile_equivalence():
    table, params, rng = _setup()
    centers, contexts, mask = _distinct_batch(rng, 256)
    got_p, got_m, want_p, want_m = _run_both(table, params, centers, contexts, mask, 256)
    np.testing.assert_allclose(got_p.syn0, want_p.syn0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_p.syn1, want_p.syn1, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(got_m.loss), float(want_m.loss), rtol=1e-5)
    np.testing.assert_allclose(
        float(got_m.mean_f_pos), float(want_m.mean_f_pos), rtol=1e-5, atol=1e-7)
    assert float(got_m.pairs) == float(want_m.pairs)


@needs_pallas
def test_multi_tile_equivalence():
    # rows globally distinct → the kernel's sequential-tile semantics coincide with
    # XLA's batch-start-value semantics even across tiles
    table, params, rng = _setup(seed=3)
    centers, contexts, mask = _distinct_batch(rng, 256)
    got_p, got_m, want_p, want_m = _run_both(table, params, centers, contexts, mask, 64)
    np.testing.assert_allclose(got_p.syn0, want_p.syn0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_p.syn1, want_p.syn1, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(got_m.loss), float(want_m.loss), rtol=1e-5)


@needs_pallas
def test_masked_rows_do_not_clobber_row0():
    """The ADVICE finding: flush-padded entries have centers/contexts == 0; their
    writeback must be skipped or a stale row-0 value can overwrite a real row-0
    update made earlier in the same tile."""
    table, params, rng = _setup(seed=5)
    B = 64
    centers, contexts, mask = _distinct_batch(rng, B)
    # a real pair touching row 0 early in the tile...
    centers[3] = 0
    contexts[5] = 0
    # ...and masked padding (centers/contexts = 0) at the end of the same tile
    centers[B - 8:] = 0
    contexts[B - 8:] = 0
    mask[B - 8:] = 0.0
    got_p, got_m, want_p, want_m = _run_both(table, params, centers, contexts, mask, B)
    np.testing.assert_allclose(got_p.syn0, want_p.syn0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_p.syn1, want_p.syn1, rtol=1e-5, atol=1e-6)
    # row 0 actually moved (the hazard scenario is exercised, not vacuous)
    assert not np.allclose(np.asarray(want_p.syn0[0]), np.asarray(params.syn0[0]))
    np.testing.assert_allclose(float(got_m.pairs), float(want_m.pairs))


@needs_pallas
def test_trainer_smoke_use_pallas():
    """use_pallas=True constructs and trains end-to-end (the round-1 wiring bug made
    this raise TypeError before the first step)."""
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.trainer import Trainer

    rng = np.random.default_rng(11)
    words = [f"w{i}" for i in range(40)]
    sentences = [[words[j] for j in rng.integers(0, 40, 12)] for _ in range(60)]
    vocab = build_vocab(sentences, min_count=1)
    cfg = Word2VecConfig(
        vector_size=16, min_count=1, pairs_per_batch=128, num_iterations=1,
        window=3, negatives=3, negative_pool=16, use_pallas=True,
        steps_per_dispatch=2, seed=2, subsample_ratio=0.0)
    plan = make_mesh(1, 1, devices=jax.devices()[:1])
    trainer = Trainer(cfg, vocab, plan=plan)
    before = np.asarray(trainer.params.syn0).copy()
    trainer.fit(encode_sentences(sentences, vocab, cfg.max_sentence_length))
    after = np.asarray(trainer.params.syn0)
    assert np.isfinite(after).all()
    assert not np.allclose(before, after)


def test_pallas_rejects_multi_device_plan():
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.trainer import Trainer

    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(16)], np.full(16, 5))
    cfg = Word2VecConfig(vector_size=8, min_count=1, use_pallas=True)
    plan = make_mesh(1, 2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="single-device"):
        Trainer(cfg, vocab, plan=plan)
