"""On-device health probe: ONE fused stats reduction over the params carry.

Extends the trainer's round-6 finiteness probe (a tiny ``isfinite().all()``
jit) into the instrumentation ROADMAP item 2 presupposes: the 1.6M-vocab
quality collapse is a FINITE norm blowup (purity 0.99 → 0.14, no NaN —
EVAL.md round-5 ladder), so the finiteness bit alone observes nothing until
long after the geometry is wrecked. The probe reads each matrix once and
returns, per matrix, over the REAL vocab rows (padding rows are zero by
construction and would poison every channel):

- ``max_norm`` / ``mean_norm`` — extremes and scale of the L2 row norms;
- ``p99_norm`` — histogram-bucketed (quarter-octave log2 buckets): an exact
  p99 needs a top-k/sort over [V], which at 10M rows is the same class of
  device work tools/model_ops_10m.py exists to avoid; the bucketed value is
  exact to one bucket (ratio ≤ 2^(1/4) ≈ 1.19), plenty for a blowup that
  moves norms by orders of magnitude;
- ``frac_over`` — fraction of rows with norm above the watchdog threshold
  (the channel the round-5 collapse is visible in long before the max).

Plus a whole-carry ``finite`` bit (over the PADDED matrices — identical
semantics to the old probe). The update-magnitude proxy (delta of
``mean_norm`` between consecutive probes) is computed host-side by the
trainer — it needs cross-probe state the pure device function cannot hold.

Collective discipline: on a sharded mesh the reductions lower to collectives,
so the caller must drain the params carry before dispatching the probe and
fetch the result explicitly ("one collective program at a time",
docs/sharding.md) — the trainer's ``_health_stats`` owns that protocol.
Norm accumulation is ≥f32 regardless of param dtype (bf16 squares underflow
and the blowup channel saturates exactly where precision matters).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

# quarter-octave log2 buckets covering 2^-12 .. 2^20 — row norms outside that
# span clamp to the edge buckets (a healthy embedding sits around 2^0..2^4;
# the measured blowup runs orders of magnitude past 2^20 only after the
# watchdog should long have fired)
_HIST_LO = -12.0          # log2 of the smallest bucket edge
_HIST_PER_OCTAVE = 4
_HIST_BUCKETS = (20 - (-12)) * _HIST_PER_OCTAVE  # 128


class MatrixStats(NamedTuple):
    """Row-norm channels of one embedding matrix (real vocab rows only)."""

    max_norm: jax.Array    # f32 scalar
    mean_norm: jax.Array   # f32 scalar
    p99_norm: jax.Array    # f32 scalar — upper edge of the p99 bucket
    frac_over: jax.Array   # f32 scalar — fraction of rows with norm > threshold


class HealthStats(NamedTuple):
    """The fused probe's full result pytree."""

    finite: jax.Array      # bool scalar over the PADDED carry (old probe bit)
    syn0: MatrixStats
    syn1: MatrixStats


def _matrix_stats(m: jax.Array, vocab_size: int, threshold: float) -> MatrixStats:
    rows = m[:vocab_size]
    norms = jnp.sqrt(jnp.sum(
        rows.astype(jnp.float32) * rows.astype(jnp.float32), axis=1))
    # histogram p99: bucket index from log2(norm), scatter-add counts, then
    # read the first bucket whose CDF crosses 99% of rows. No [V] sort/top-k.
    logn = jnp.log2(jnp.maximum(norms, jnp.float32(2.0 ** _HIST_LO)))
    idx = jnp.clip(
        jnp.floor((logn - _HIST_LO) * _HIST_PER_OCTAVE),
        0, _HIST_BUCKETS - 1).astype(jnp.int32)
    hist = jnp.zeros(_HIST_BUCKETS, jnp.int32).at[idx].add(1)
    # int32 CDF is exact to 2^31 rows — far past the 10M-row north star
    cdf = jnp.cumsum(hist.astype(jnp.int32))
    k = jnp.argmax(cdf >= jnp.int32(-(-vocab_size * 99 // 100)))
    p99 = jnp.exp2((k.astype(jnp.float32) + 1.0) / _HIST_PER_OCTAVE + _HIST_LO)
    return MatrixStats(
        max_norm=jnp.max(norms),
        mean_norm=jnp.mean(norms),
        p99_norm=p99,
        frac_over=jnp.mean((norms > jnp.float32(threshold)).astype(jnp.float32)),
    )


def make_health_probe(vocab_size: int, threshold: float) -> Callable:
    """Build the jitted fused probe: ``fn(params) -> HealthStats``.

    ``vocab_size`` (static) masks the padding rows out of every norm channel;
    ``threshold`` (static — a config constant, so baking it in costs no
    recompile churn) defines the ``frac_over`` channel."""

    def probe(params) -> HealthStats:
        finite = (jnp.isfinite(params.syn0).all()
                  & jnp.isfinite(params.syn1).all())
        return HealthStats(
            finite=finite,
            syn0=_matrix_stats(params.syn0, vocab_size, threshold),
            syn1=_matrix_stats(params.syn1, vocab_size, threshold),
        )

    return jax.jit(probe)


def stats_to_channels(stats: "HealthStats") -> dict:
    """Flatten a FETCHED (host-side) HealthStats into the plain-float channel
    dict the heartbeat/sink/watchdog layers consume."""
    out = {"finite": bool(stats.finite)}
    for name in ("syn0", "syn1"):
        ms = getattr(stats, name)
        out[name] = {
            "max_norm": float(ms.max_norm),
            "mean_norm": float(ms.mean_norm),
            "p99_norm": float(ms.p99_norm),
            "frac_over": float(ms.frac_over),
        }
    return out
