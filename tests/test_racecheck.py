"""graftrace dynamic half (ISSUE 20): the lockcheck wrappers detect what
they claim (rank inversions, non-reentrant re-acquisition, reentrant rlock
tolerance), checking OFF is zero-cost (raw primitive types, zero wrapper
objects), the racecheck smoke passes end-to-end as a subprocess (the tier-1
wiring), the statusd-scrape + blackbox-dump + sink-rotation triple survives
three concurrent hammer threads, and the shutdown-hygiene satellite holds
(close-twice, close-during-inflight, leaked-thread surfacing)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from glint_word2vec_tpu import lockcheck  # noqa: E402


@pytest.fixture()
def checked():
    """Enable instrumentation for one test, restore the off default after."""
    lockcheck.configure(enabled=True, seed=7, perturb=0.0)
    lockcheck.reset()
    yield lockcheck
    lockcheck.configure(enabled=False, perturb=0.0)
    lockcheck.reset()


# -- zero cost off ---------------------------------------------------------------------


def test_off_mode_returns_raw_primitives_and_allocates_nothing():
    assert not lockcheck.enabled()
    before = lockcheck.wrappers_allocated()
    assert type(lockcheck.make_lock("serve.handle")) is type(threading.Lock())
    assert type(lockcheck.make_rlock("obs.sink")) is type(threading.RLock())
    assert isinstance(lockcheck.make_condition("serve.batcher.cv"),
                      threading.Condition)
    assert lockcheck.wrappers_allocated() == before


# -- the wrappers ----------------------------------------------------------------------


def test_unregistered_name_refused_when_checking(checked):
    with pytest.raises(KeyError, match="LOCK_TABLE"):
        checked.make_lock("no.such.lock")
    with pytest.raises(ValueError, match="kind"):
        checked.make_rlock("serve.handle")  # registered as plain lock


def test_rank_inversion_detected_and_ordered_nesting_clean(checked):
    outer = checked.make_lock("fleet.router")     # rank 30
    inner = checked.make_rlock("obs.sink")        # rank 90
    with outer:
        with inner:
            pass
    rep = checked.report()
    assert rep["inversions"] == []
    assert "fleet.router->obs.sink" in rep["edges"]
    with inner:
        with outer:  # rank 30 while holding rank 90: inversion
            pass
    rep = checked.report()
    assert any(i["kind"] == "rank-inversion"
               and i["held"] == "obs.sink"
               and i["acquiring"] == "fleet.router"
               for i in rep["inversions"]), rep


def test_rlock_reentry_tolerated_lock_reentry_flagged(checked):
    r = checked.make_rlock("obs.blackbox")
    with r:
        with r:  # reentrant rlock: no self-edge, no finding
            pass
    assert checked.report()["inversions"] == []
    lk = checked.make_lock("serve.handle")
    lk.acquire()
    try:
        # a second blocking acquire would deadlock the test; the checker
        # must flag the attempt even through the non-blocking path once
        # the lock shows up as held by this thread
        got = lk.acquire(blocking=False)
        assert not got
    finally:
        lk.release()


def test_condition_wait_counts_held_while_blocking(checked):
    guard = checked.make_lock("fleet.router")
    cv = checked.make_condition("serve.batcher.cv")

    def waiter():
        with guard:          # holding one lock...
            with cv:
                cv.wait(timeout=0.05)   # ...while blocking on another

    t = threading.Thread(target=waiter)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    rep = checked.report()
    assert rep["held_while_blocking"] >= 1
    assert "fleet.router->serve.batcher.cv" in \
        rep["held_while_blocking_pairs"]


def test_perturber_is_seeded_and_counts_yields(checked):
    checked.configure(perturb=1.0, seed=3)
    lk = checked.make_lock("serve.handle")
    for _ in range(10):
        with lk:
            pass
    rep = checked.report()
    assert rep["perturb_yields"] >= 10


# -- the tool (tier-1 smoke wiring) ----------------------------------------------------


def test_racecheck_smoke_subprocess_one_json_line():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("GLINT_LOCKCHECK", None)  # the tool owns enabling
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "racecheck.py"),
         "--smoke", "--duration", "0.8", "--perturb", "0.05"],
        capture_output=True, text=True, timeout=420, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout  # the R7 contract
    payload = json.loads(lines[0])
    assert payload["ok"] and payload["tool"] == "racecheck"
    assert payload["zero_cost"]["wrappers_allocated"] == 0
    assert payload["zero_cost"]["raw_types"]
    assert payload["lockcheck"]["acquisitions"] > 0
    assert payload["lockcheck"]["inversions"] == []
    assert payload["inversions_unbaselined"] == []
    assert payload["lockcheck"]["reloads_observed"] >= 1


# -- satellite 3: the scrape + dump + rotation triple ----------------------------------


def test_concurrent_scrape_dump_rotation_triple(tmp_path):
    """statusd scrape + blackbox dump + sink rotation hammering the same
    rings from three threads (seeded, bounded): no exception anywhere, the
    scrape stays parseable, the rotated telemetry stays schema-valid."""
    import urllib.request

    from glint_word2vec_tpu.obs.blackbox import FlightRecorder
    from glint_word2vec_tpu.obs.schema import validate_record
    from glint_word2vec_tpu.obs.sink import TelemetrySink
    from glint_word2vec_tpu.obs.statusd import StatusServer

    tele = str(tmp_path / "t.jsonl")
    sink = TelemetrySink(tele, rotate_bytes=2048)  # tiny: force rotations
    sink.emit("run_start", config={}, host={})
    rec = FlightRecorder(tele + ".blackbox.json", ring=64)
    srv = StatusServer(0, lambda: {"status": "running", "global_step": 1,
                                   "heartbeats": 2}).start()
    errors = []
    stop = threading.Event()
    rng = np.random.default_rng(11)

    def guard(fn):
        def run():
            try:
                while not stop.is_set():
                    fn()
            except Exception as e:  # noqa: BLE001 — any raise fails
                errors.append(f"{type(e).__name__}: {e}")
        return run

    def scrape():
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/status.json", timeout=5).read()

    def dump():
        rec.observe("heartbeat", {"schema": 1, "t": 0.0, "kind": "heartbeat",
                                  "step": 1})
        rec.dump({"kind": "test"})

    def rotate():
        sink.emit("heartbeat", step=int(rng.integers(0, 100)), words=64,
                  alpha=0.025, loss=0.1, mean_f_pos=0.5,
                  pairs_per_sec=1000.0, host_wait_s=0.0, dispatch_s=0.0)

    threads = [threading.Thread(target=guard(f))
               for f in (scrape, dump, rotate)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads)
    srv.stop()
    sink.close()
    assert errors == [], errors
    rotated = [p for p in os.listdir(tmp_path) if ".jsonl." in p]
    assert rotated, "rotate_bytes=2048 never rotated under the hammer"
    with open(tele, "r", encoding="utf-8") as f:
        for line in f:
            rec_obj = json.loads(line)
            assert validate_record(rec_obj) == [], rec_obj
    with open(tele + ".blackbox.json", "r", encoding="utf-8") as f:
        assert json.load(f)["cause"]["kind"] == "test"


# -- satellite 1: shutdown hygiene -----------------------------------------------------


def _toy_service(**kw):
    import jax.numpy as jnp

    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.serve import EmbeddingService

    v, d = 50, 8
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(v)], np.ones(v, np.int64))
    m = np.random.default_rng(0).standard_normal((v, d)).astype(np.float32)
    model = Word2VecModel(vocab, jnp.asarray(m))
    return EmbeddingService(model=model, ann=False, **kw)


def test_service_close_twice_and_stats_surface_leaks():
    svc = _toy_service()
    assert svc.stats()["leaked_threads"] == 0
    assert svc.close() == 0
    assert svc.close() == 0  # idempotent, same answer


def test_service_close_during_inflight():
    """Queries in flight when close() lands must not wedge the shutdown:
    the batcher drains admitted work, close joins within its bound, and
    no thread leaks."""
    svc = _toy_service(max_batch=4, max_delay_ms=20.0)
    results, errs = [], []

    def q():
        try:
            results.append(svc.vector("w1", timeout=30.0))
        except Exception as e:  # noqa: BLE001 — refusal after close is fine
            errs.append(type(e).__name__)

    threads = [threading.Thread(target=q) for _ in range(8)]
    for t in threads:
        t.start()
    leaked = svc.close()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads)
    assert leaked == 0
    # every in-flight query either completed or was refused — none hung
    assert len(results) + len(errs) == 8


def test_fleet_close_twice_and_leak_surfacing():
    from glint_word2vec_tpu.serve import FleetRouter, ReplicaSet

    services = [_toy_service() for _ in range(2)]
    rset = ReplicaSet.adopt(services)
    router = FleetRouter(rset, probe_s=0.05)
    try:
        assert router.stats()["leaked_threads"] == 0
        for rstats in router.stats()["replicas"].values():
            assert rstats["leaked_threads"] == 0
    finally:
        router.close()
        router.close()  # idempotent
    assert router.stats()["leaked_threads"] == 0
