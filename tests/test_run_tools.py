"""Driver-tool suite for the observability consumption half
(docs/observability.md §7–8): telemetry_tail renders a run log for humans,
run_report folds it into ONE machine JSON line (R7) and flags truncation,
and perfgate's self-test proves the regression gate fires on a seeded
regression while passing the genuine committed bench line."""

import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def run_log(tmp_path_factory):
    """One shared telemetry-on toy fit; returns the sink JSONL path."""
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer
    path = str(tmp_path_factory.mktemp("telemetry") / "run.jsonl")
    rng = np.random.default_rng(0)
    sents = [[f"w{i}" for i in rng.integers(0, 30, 20)] for _ in range(250)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=8, pairs_per_batch=128, window=3,
                         num_iterations=2, steps_per_dispatch=2,
                         heartbeat_every_steps=2, subsample_ratio=0.0,
                         prefetch_chunks=0, seed=1, telemetry_path=path)
    Trainer(cfg, vocab).fit(encode_sentences(sents, vocab, 1000))
    return path


def _run(args, **kw):
    return subprocess.run(
        [sys.executable] + args, cwd=_REPO, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=300, **kw)


# -- telemetry_tail --------------------------------------------------------------------


def test_telemetry_tail_summarizes(run_log):
    proc = _run([os.path.join(_REPO, "tools", "telemetry_tail.py"),
                 run_log, "--last", "5"])
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "pairs/s: median" in out
    assert "run_start=1" in out and "run_end=1" in out
    assert "phase dispatch" in out  # the attribution windows render
    assert "status ok" in out


# -- run_report ------------------------------------------------------------------------


def test_run_report_one_json_line(run_log):
    proc = _run([os.path.join(_REPO, "tools", "run_report.py"), run_log])
    assert proc.returncode == 0, proc.stderr
    lines = [line for line in proc.stdout.splitlines() if line.strip()]
    assert len(lines) == 1, "R7: exactly one stdout line"
    rep = json.loads(lines[0])
    assert rep["ok"] and rep["status"] == "ok" and rep["schema_valid"]
    assert rep["heartbeats"] >= 1
    assert rep["pairs_per_sec"]["median"] > 0
    assert rep["phases"]["dispatch"]["count"] > 0
    assert rep["norms"]["syn0"]["max"] > 0
    assert rep["lr_scale_final"] == 1.0


def test_run_report_flags_truncated_log(run_log, tmp_path):
    """A log with no run_end is the crash signature — the report must say
    'truncated' and exit nonzero so a remote driver can alarm on it."""
    truncated = str(tmp_path / "trunc.jsonl")
    with open(run_log) as src, open(truncated, "w") as dst:
        for line in src:
            if json.loads(line)["kind"] != "run_end":
                dst.write(line)
    proc = _run([os.path.join(_REPO, "tools", "run_report.py"), truncated])
    assert proc.returncode == 1
    rep = json.loads(proc.stdout.strip())
    assert rep["status"] == "truncated" and not rep["ok"]
    # steps + phases still reconstructed from the heartbeat windows
    assert rep["steps"] > 0
    assert rep["phases"].get("dispatch", {}).get("count", 0) > 0


def test_run_report_folds_blackbox(run_log, tmp_path):
    """--blackbox validates + embeds the dump's terminal cause."""
    from glint_word2vec_tpu.obs.blackbox import FlightRecorder
    dump = str(tmp_path / "x.blackbox.json")
    rec = FlightRecorder(dump)
    rec.begin_run("r1")
    rec.dump(FlightRecorder.signal_cause(15))
    proc = _run([os.path.join(_REPO, "tools", "run_report.py"), run_log,
                 "--blackbox", dump])
    rep = json.loads(proc.stdout.strip())
    assert rep["blackbox"]["valid"]
    assert rep["blackbox"]["cause"]["signal"] == "SIGTERM"


# -- perfgate --------------------------------------------------------------------------


def test_perfgate_smoke_self_test():
    """Acceptance: the genuine current bench line passes the tolerance
    bands, the seeded regression fires — one JSON line, exit 0."""
    proc = _run([os.path.join(_REPO, "tools", "perfgate.py"), "--smoke"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [line for line in proc.stdout.splitlines() if line.strip()]
    assert len(lines) == 1, "R7: exactly one stdout line"
    rep = json.loads(lines[0])
    assert rep["ok"] and rep["mode"] == "smoke"
    assert rep["genuine"]["ok"], rep["genuine"]
    assert not rep["seeded"]["ok"]
    assert "value" in rep["seeded"]["fired_on"]
    assert len(rep["rungs"]) >= 2


def test_perfgate_smoke_fails_if_seed_does_not_fire():
    """seed-factor 1.0 = no regression seeded: the self-test must then FAIL
    (a gate that can't fire is worse than no gate)."""
    proc = _run([os.path.join(_REPO, "tools", "perfgate.py"), "--smoke",
                 "--seed-factor", "1.0"])
    assert proc.returncode == 1
    rep = json.loads(proc.stdout.strip())
    assert not rep["ok"] and rep["seeded"]["ok"]


def test_perfgate_gates_a_fresh_bench_file(tmp_path):
    """Real mode: an in-band fresh line passes, a regressed one fails, and
    both accept the RAW bench.py line shape (no driver wrapper). The genuine
    sample is the LATEST committed rung — the gate's ref — so the test keeps
    working as rungs (and newly-gated fields, e.g. the ISSUE-14 restructured
    step rows r06 introduced) accrete."""
    latest = sorted(glob.glob(os.path.join(_REPO, "BENCH_r*.json")))[-1]
    genuine = json.load(open(latest))["parsed"]
    good = str(tmp_path / "good.json")
    json.dump(genuine, open(good, "w"))
    proc = _run([os.path.join(_REPO, "tools", "perfgate.py"),
                 "--bench", good])
    assert proc.returncode == 0, proc.stdout
    assert json.loads(proc.stdout.strip())["ok"]

    bad = str(tmp_path / "bad.json")
    json.dump({**genuine, "value": genuine["value"] * 0.5}, open(bad, "w"))
    proc = _run([os.path.join(_REPO, "tools", "perfgate.py"), "--bench", bad])
    assert proc.returncode == 1
    rep = json.loads(proc.stdout.strip())
    assert not rep["metrics"]["value"]["ok"]
    assert rep["metrics"]["e2e_pairs_per_sec"]["ok"]  # untouched metric holds
