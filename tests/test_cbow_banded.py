"""Equivalence suite for the banded CBOW step (ops/cbow_banded.py).

The banded path must produce the SAME update as the shipped scatter step
``cbow_step_shared_core`` on the same example set — it is a perf restructuring,
not a new estimator. The suite pins that across the cases the formulation could
get wrong: dynamic per-position windows, sentence boundaries inside a block,
subsampled (kept) streams, padded tails, and — the banded-only hazard — examples
whose windows cross a chunk cut (the ±window halo must make them exact).

Float64 runs (via jax.experimental.enable_x64) hold the two formulations to
~1e-12: at that tolerance any dropped/double-counted context link or off-by-one
interval endpoint is a hard failure, not noise.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from glint_word2vec_tpu.data.hashrng import (
    STREAM_SUBSAMPLE,
    STREAM_WINDOW,
    hash_u01_at,
    stream_base,
)
from glint_word2vec_tpu.data.pipeline import (
    _subsample_and_window,
    pack_halo_token_blocks,
)
from glint_word2vec_tpu.ops.cbow_banded import cbow_step_banded_core, cumsum_rows
from glint_word2vec_tpu.ops.pairgen import device_cbow_windows
from glint_word2vec_tpu.ops.sgns import EmbeddingPair, cbow_step_shared_core

SEED, IT, SHARD = 7, 1, 0


# ---------------------------------------------------------------------------
# corpus / reference helpers
# ---------------------------------------------------------------------------


def _kept_stream(rng, vocab, n_sentences, max_len, subsample=0.0):
    """A random-sentence corpus reduced to its kept-token stream exactly like
    the trainer's packer (_device_seg_blocks): raw-ordinal-keyed hash
    subsample, sentence-start flags on the kept stream."""
    lens = rng.integers(1, max_len, n_sentences)
    toks = rng.integers(0, vocab, lens.sum()).astype(np.int32)
    sids = np.repeat(np.arange(n_sentences), lens)
    if subsample > 0:
        sub_base = stream_base(SEED, STREAM_SUBSAMPLE, IT, SHARD)
        # a crude keep curve is enough — the test only needs SOME tokens gone
        keep = np.minimum(
            0.2 + 0.8 * rng.random(vocab), 1.0).astype(np.float32)
        u = hash_u01_at(sub_base, np.arange(toks.shape[0], dtype=np.uint64))
        m = u <= keep[toks]
        toks, sids = toks[m], sids[m]
    if toks.shape[0] == 0:
        return toks, np.zeros(0, bool)
    starts = np.empty(toks.shape[0], bool)
    starts[0] = True
    starts[1:] = sids[1:] != sids[:-1]
    return toks, starts


def _host_windows(ktoks, starts, window):
    """(left, right) per kept position — the host mirror the device derivation
    must match: pipeline._subsample_and_window on the kept stream (keep ≡ 1,
    ordinals = kept ordinals, the presubsampled-feed keying)."""
    lens = np.diff(np.concatenate(
        [np.flatnonzero(starts), [ktoks.shape[0]]])).astype(np.int64)
    out = _subsample_and_window(
        ktoks, lens, np.ones(int(ktoks.max()) + 1, np.float32), window,
        SEED, IT, SHARD, 0, True)
    toks2, left, total, nk = out
    np.testing.assert_array_equal(toks2, ktoks)
    return left.astype(np.int64), (total - left).astype(np.int64)


def _scatter_reference(params, ktoks, left, right, sel, negatives, alpha,
                       num_negatives, window, dtype):
    """One cbow_step_shared_core step over the stream positions in ``sel``."""
    C = 2 * window
    nb = len(sel)
    ctx = np.zeros((nb, C), np.int32)
    ctxm = np.zeros((nb, C), np.float32)
    for i, b in enumerate(sel):
        idx = (list(range(b - left[b], b))
               + list(range(b + 1, b + right[b] + 1)))
        ctx[i, :len(idx)] = ktoks[idx]
        ctxm[i, :len(idx)] = 1.0
    return cbow_step_shared_core(
        params, jnp.asarray(ktoks[sel].astype(np.int32)), jnp.asarray(ctx),
        jnp.asarray(ctxm), jnp.ones(nb, jnp.float32), negatives, alpha,
        num_negatives, "exact", dtype)


def _banded_blocks(ktoks, starts, T, window):
    """Halo blocks + device window derivation for each, as the trainer feeds
    them (win_base keyed like the presubsampled device feed)."""
    win_base = stream_base(SEED, STREAM_WINDOW, IT, SHARD)
    out = []
    for tb, bits, nv, ob, nc in pack_halo_token_blocks(
            [(ktoks, starts)], T, window, np.int32):
        band = device_cbow_windows(
            jnp.asarray(tb), jnp.asarray(bits), jnp.int32(nv),
            jnp.uint32(ob & 0xFFFFFFFF), jnp.uint32(ob >> 32),
            jnp.uint32(win_base), window=window, halo=window)
        out.append((tb, band, nc))
    return out


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------


def test_cumsum_rows_matches_numpy():
    rng = np.random.default_rng(0)
    for T, D in ((1, 3), (127, 8), (128, 8), (300, 7), (1000, 5)):
        x = rng.normal(size=(T, D)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(cumsum_rows(jnp.asarray(x))), np.cumsum(x, axis=0),
            rtol=1e-5, atol=1e-5)


def test_halo_blocks_cover_every_token_once():
    rng = np.random.default_rng(1)
    ktoks, starts = _kept_stream(rng, 50, 30, 12)
    L = ktoks.shape[0]
    H, T = 4, 20
    Tc = T - 2 * H
    blocks = list(pack_halo_token_blocks([(ktoks, starts)], T, H, np.int32))
    assert sum(b[4] for b in blocks) == L          # every token a core once
    covered = 0
    for tb, bits, nv, ob, nc in blocks:
        # the core slots hold exactly the next nc stream tokens
        np.testing.assert_array_equal(
            tb[H:H + nc], ktoks[covered:covered + nc])
        assert nc <= Tc
        assert nv <= T
        # ordinal base points H before the first core slot's stream position
        assert (ob - ((covered - H) & 0xFFFFFFFFFFFFFFFF)
                ) % (1 << 64) == 0
        covered += nc
    # streams shorter than one block still emit their cores
    short = list(pack_halo_token_blocks(
        [(ktoks[:3], starts[:3])], T, H, np.int32))
    assert sum(b[4] for b in short) == 3
    # empty stream emits nothing
    assert list(pack_halo_token_blocks([], T, H, np.int32)) == []


def test_device_windows_match_host_across_blocks():
    """The chunk-edge case: device-derived (left, right) of every CORE slot —
    including slots whose window crosses a block cut and lives in the halo —
    must equal the host pipeline's sentence-clamped extents."""
    rng = np.random.default_rng(2)
    W = 4
    ktoks, starts = _kept_stream(rng, 60, 40, 14)
    left_h, right_h = _host_windows(ktoks, starts, W)
    covered = 0
    for tb, band, nc in _banded_blocks(ktoks, starts, 3 * W + 9, W):
        lb, rb = np.asarray(band.left), np.asarray(band.right)
        cm = np.asarray(band.center)
        core = slice(W, W + nc)
        np.testing.assert_array_equal(
            lb[core], left_h[covered:covered + nc])
        np.testing.assert_array_equal(
            rb[core], right_h[covered:covered + nc])
        assert cm[core].all()
        assert cm[:W].sum() == 0 and cm[W + nc:].sum() == 0
        covered += nc
    assert covered == ktoks.shape[0]


# ---------------------------------------------------------------------------
# step equivalence vs the scatter oracle
# ---------------------------------------------------------------------------


def _equivalence_case(dtype, rtol, atol, subsample):
    rng = np.random.default_rng(3)
    V, D, P, W, NEG = 120, 16, 32, 3, 4
    ktoks, starts = _kept_stream(rng, 40, 15, V, subsample=subsample)
    left_h, right_h = _host_windows(ktoks, starts, W)
    live = np.flatnonzero(left_h + right_h > 0)
    assert live.size > 20   # the case actually exercises dynamic windows

    params0 = EmbeddingPair(
        jnp.asarray(rng.normal(0, 0.1, (V, D)), dtype),
        jnp.asarray(rng.normal(0, 0.05, (V, D)), dtype))
    negs = jnp.asarray(rng.integers(0, V, P), jnp.int32)
    alpha = jnp.asarray(0.05, dtype)

    # --- single block: everything (sentences, padding tail) in one step -----
    T = ktoks.shape[0] + 2 * W + 5
    ((tb, band, nc),) = _banded_blocks(ktoks, starts, T, W)
    p_band, m_band = cbow_step_banded_core(
        params0, jnp.asarray(tb), band.left, band.right, band.center,
        band.token, negs, alpha, NEG, W, "exact", dtype)
    p_ref, m_ref = _scatter_reference(
        params0, ktoks, left_h, right_h, live, negs, alpha, NEG, W, dtype)
    np.testing.assert_allclose(
        np.asarray(p_band.syn0), np.asarray(p_ref.syn0), rtol=rtol, atol=atol)
    np.testing.assert_allclose(
        np.asarray(p_band.syn1), np.asarray(p_ref.syn1), rtol=rtol, atol=atol)
    np.testing.assert_allclose(
        float(m_band.loss), float(m_ref.loss), rtol=max(rtol, 1e-6))
    assert float(m_band.pairs) == float(m_ref.pairs) == live.size

    # --- multi block: sequential steps, windows crossing every cut ----------
    p_cur = params0
    p_refc = params0
    covered = 0
    for tb, band, nc in _banded_blocks(ktoks, starts, 4 * W + 6, W):
        p_cur, _ = cbow_step_banded_core(
            p_cur, jnp.asarray(tb), band.left, band.right, band.center,
            band.token, negs, alpha, NEG, W, "exact", dtype)
        sel = live[(live >= covered) & (live < covered + nc)]
        covered += nc
        if sel.size:
            p_refc, _ = _scatter_reference(
                p_refc, ktoks, left_h, right_h, sel, negs, alpha, NEG, W,
                dtype)
    np.testing.assert_allclose(
        np.asarray(p_cur.syn0), np.asarray(p_refc.syn0),
        rtol=rtol * 5, atol=atol * 5)
    np.testing.assert_allclose(
        np.asarray(p_cur.syn1), np.asarray(p_refc.syn1),
        rtol=rtol * 5, atol=atol * 5)


def test_banded_equals_scatter_float32():
    _equivalence_case(jnp.float32, 2e-5, 2e-6, subsample=0.0)


def test_banded_equals_scatter_float32_subsampled():
    _equivalence_case(jnp.float32, 2e-5, 2e-6, subsample=1e-1)


def test_banded_equals_scatter_float64_tight():
    """float64 on CPU: any structural mismatch (lost/duplicated context link,
    off-by-one interval) is far above 1e-12 — this is the exactness pin."""
    from jax.experimental import enable_x64
    with enable_x64():
        _equivalence_case(jnp.float64, 1e-12, 1e-14, subsample=0.0)


def test_banded_metrics_elided_twin_bit_identical():
    """with_metrics=False must change ONLY the loss side-channel — trained
    params bit-identical (the trainer's fast-twin contract)."""
    rng = np.random.default_rng(5)
    V, D, P, W, NEG = 80, 8, 16, 3, 3
    ktoks, starts = _kept_stream(rng, 20, 12, V)
    T = ktoks.shape[0] + 2 * W + 3
    ((tb, band, nc),) = _banded_blocks(ktoks, starts, T, W)
    params0 = EmbeddingPair(
        jnp.asarray(rng.normal(0, 0.1, (V, D)), jnp.float32),
        jnp.asarray(rng.normal(0, 0.05, (V, D)), jnp.float32))
    negs = jnp.asarray(rng.integers(0, V, P), jnp.int32)
    args = (params0, jnp.asarray(tb), band.left, band.right, band.center,
            band.token, negs, jnp.float32(0.05), NEG, W, "exact", jnp.float32,
            jnp.float32)
    p_full, m_full = cbow_step_banded_core(*args, with_metrics=True)
    p_fast, m_fast = cbow_step_banded_core(*args, with_metrics=False)
    np.testing.assert_array_equal(np.asarray(p_full.syn0),
                                  np.asarray(p_fast.syn0))
    np.testing.assert_array_equal(np.asarray(p_full.syn1),
                                  np.asarray(p_fast.syn1))
    assert float(m_fast.loss) == 0.0
    assert float(m_fast.pairs) == float(m_full.pairs)


def test_banded_scatter_fallback_for_large_windows():
    """windows past the shifted-add unroll bound take the 2T-row scatter form
    of the endpoint accumulation — same update either way."""
    from glint_word2vec_tpu.ops import cbow_banded

    rng = np.random.default_rng(6)
    V, D, P, W, NEG = 100, 8, 16, 4, 3
    ktoks, starts = _kept_stream(rng, 20, 20, V)
    T = ktoks.shape[0] + 2 * W + 1
    ((tb, band, nc),) = _banded_blocks(ktoks, starts, T, W)
    params0 = EmbeddingPair(
        jnp.asarray(rng.normal(0, 0.1, (V, D)), jnp.float32),
        jnp.asarray(rng.normal(0, 0.05, (V, D)), jnp.float32))
    negs = jnp.asarray(rng.integers(0, V, P), jnp.int32)
    args = (params0, jnp.asarray(tb), band.left, band.right, band.center,
            band.token, negs, jnp.float32(0.05), NEG, W)
    p_shift, _ = cbow_step_banded_core(*args)
    orig = cbow_banded._SHIFT_UNROLL_MAX_WINDOW
    try:
        cbow_banded._SHIFT_UNROLL_MAX_WINDOW = 0  # force the scatter form
        p_scat, _ = cbow_step_banded_core(*args)
    finally:
        cbow_banded._SHIFT_UNROLL_MAX_WINDOW = orig
    np.testing.assert_allclose(np.asarray(p_shift.syn0),
                               np.asarray(p_scat.syn0), rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(p_shift.syn1),
                               np.asarray(p_scat.syn1), rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# trainer integration + config matrix
# ---------------------------------------------------------------------------


def _toy_fit(cbow_update):
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer

    rng = np.random.default_rng(3)
    words = [f"w{i}" for i in range(60)]
    sentences = [[words[j] for j in rng.integers(0, 60, 15)]
                 for _ in range(150)]
    vocab = build_vocab(sentences, min_count=1)
    cfg = Word2VecConfig(
        vector_size=16, min_count=1, pairs_per_batch=256, num_iterations=2,
        window=3, negatives=3, cbow=True, cbow_update=cbow_update,
        negative_pool=128, steps_per_dispatch=2, seed=2,
        subsample_ratio=1e-2, heartbeat_every_steps=4)
    t = Trainer(cfg, vocab)
    before = np.asarray(t.params.syn0).copy()
    t.fit(encode_sentences(sentences, vocab, 1000))
    return t, before


def test_trainer_fit_banded_smoke():
    t, before = _toy_fit("banded")
    after = np.asarray(t.params.syn0)
    assert np.isfinite(after).all()
    assert not np.allclose(before, after)
    assert t.pairs_trained > 0
    assert t.heartbeats and np.isfinite(t.heartbeats[-1].loss)
    # the metrics-elided fast twin is actually wired for this path
    assert t._step_fn_fast is not t._step_fn


def test_trainer_fit_banded_deterministic():
    t1, _ = _toy_fit("banded")
    t2, _ = _toy_fit("banded")
    np.testing.assert_array_equal(np.asarray(t1.params.syn0),
                                  np.asarray(t2.params.syn0))


def test_config_selection_matrix_errors():
    from glint_word2vec_tpu.config import Word2VecConfig

    for kw, msg in [
        (dict(cbow_update="banded"), "requires cbow=True"),
        (dict(cbow=True, cbow_update="banded", duplicate_scaling=True),
         "duplicate_scaling"),
        (dict(cbow=True, cbow_update="banded", negative_pool=0),
         "shared-pool"),
        (dict(cbow=True, cbow_update="banded", use_pallas=True), "pallas"),
        (dict(cbow=True, cbow_update="banded", tokens_per_step=64),
         "tokens_per_step"),
        (dict(cbow=True, cbow_update="banded", window=1), "window"),
        (dict(cbow=True, cbow_update="bogus"), "cbow_update"),
        (dict(cbow=True, duplicate_scaling=True, negative_pool=256),
         "per-example"),
    ]:
        with pytest.raises(ValueError, match=msg.replace("(", "\\(")):
            Word2VecConfig(**kw)
    # AUTO pool resolutions around the matrix
    assert Word2VecConfig(cbow=True, duplicate_scaling=True).negative_pool == 0
    assert Word2VecConfig(cbow=True, cbow_update="banded",
                          pairs_per_batch=256).negative_pool > 0
    # scatter stays the default
    assert Word2VecConfig(cbow=True).cbow_update == "scatter"


def test_trainer_banded_config_roundtrip():
    """cbow_update survives to_dict/from_dict (checkpoint metadata)."""
    from glint_word2vec_tpu.config import Word2VecConfig

    cfg = Word2VecConfig(cbow=True, cbow_update="banded")
    d = cfg.to_dict(auto_markers=False)
    assert d["cbow_update"] == "banded"
    assert Word2VecConfig.from_dict(d).cbow_update == "banded"


def test_from_dict_normalizes_legacy_ignored_pool():
    """Pre-selection-matrix checkpoints could store cbow + duplicate_scaling +
    a RESOLVED auto pool (the old trainer warn-ignored it and sampled
    per-example). from_dict must normalize that to pool=0 — the semantics the
    model actually trained with — instead of refusing to load the checkpoint."""
    from glint_word2vec_tpu.config import Word2VecConfig

    legacy = Word2VecConfig(cbow=True, pairs_per_batch=65536).to_dict(
        auto_markers=False)
    assert legacy["negative_pool"] > 0          # the resolved auto pool
    legacy["duplicate_scaling"] = True          # the pre-change combination
    cfg = Word2VecConfig.from_dict(legacy)
    assert cfg.negative_pool == 0
    # but a banded checkpoint keeps its pool (banded never ignored it), and
    # banded+duplicate_scaling still refuses (it never existed to preserve)
    banded = Word2VecConfig(cbow=True, cbow_update="banded").to_dict(
        auto_markers=False)
    assert Word2VecConfig.from_dict(banded).negative_pool > 0


def test_replace_rederives_auto_pool_across_path_switches():
    """replace() must re-run the AUTO pool rule when the update path changes,
    not freeze the previously resolved value into a refused combination."""
    from glint_word2vec_tpu.config import Word2VecConfig

    cfg = Word2VecConfig(cbow=True, pairs_per_batch=65536)
    assert cfg.negative_pool > 0
    assert cfg.replace(duplicate_scaling=True).negative_pool == 0
    small = Word2VecConfig(cbow=True, pairs_per_batch=128)
    assert small.negative_pool == 0
    assert small.replace(cbow_update="banded").negative_pool > 0
    # an EXPLICIT pool is never silently rewritten — the refusal stands
    explicit = Word2VecConfig(cbow=True, negative_pool=256,
                              pairs_per_batch=65536)
    with pytest.raises(ValueError, match="per-example"):
        explicit.replace(duplicate_scaling=True)
