"""Model ops at 10M vocabulary (VERDICT r4 item 5) — the reference's core scale
claim (README.md:4-9: vocabularies beyond single-machine worker memory)
demonstrated END TO END, not just as a step benchmark.

Two phases, because this environment's host<->device link is a ~9 MB/s remote
tunnel (PERF.md §5) that makes ANY 7.7 GB matrix transfer infeasible (~14 h):

  --phase host   (run with JAX_PLATFORMS=cpu + 8 virtual devices)
      The IO ops on a host-resident 10M x 384 bf16 matrix placed on an 8-way
      row-sharded mesh: row-shards save -> streamed mmap load onto the mesh ->
      find_synonyms_batch -> export_word2vec (binary). Disk + host-RAM bound —
      the same code path a real pod host runs, minus the fast PCIe hop.
  --phase device (run against the real TPU)
      The device-resident ops at 10M rows on one v5e chip: syn0 bf16 lives in
      HBM (7.7 GB of 16), find_synonyms / find_synonyms_batch at full vocab.
      Save/export are NOT run here: they would ship 7.7 GB through the 9 MB/s
      tunnel. On a real host (PCIe at GB/s) the host-phase timings apply after
      a ~seconds device->host fetch; that estimate is labeled as such.

Prints one JSON line per phase; tables to stderr. Peak RSS is reported via
resource.getrusage (linux: KB).
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V = 10_000_000
D = 384  # lane-padded production width (vector_size 384 keeps export honest)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def peak_gb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def build_vocab():
    from glint_word2vec_tpu.data.vocab import Vocabulary
    t0 = time.perf_counter()
    counts = np.maximum(1e10 / (np.arange(V) + 10.0) ** 1.07, 5.0).astype(np.int64)
    words = np.char.add("w", np.arange(V).astype("U8")).tolist()
    vocab = Vocabulary.from_words_and_counts(words, counts)
    log(f"vocab build ({V:,} types): {time.perf_counter() - t0:.1f}s "
        f"(host, rss {peak_gb():.1f} GB)")
    return vocab


def host_syn0():
    import ml_dtypes
    t0 = time.perf_counter()
    out = np.empty((V, D), ml_dtypes.bfloat16)
    rng = np.random.default_rng(0)
    for s in range(0, V, 1_000_000):  # blockwise: avoid a 15 GB f32 transient
        out[s:s + 1_000_000] = rng.standard_normal(
            (min(1_000_000, V - s), D), np.float32).astype(ml_dtypes.bfloat16)
    log(f"syn0 host build [{V:,} x {D}] bf16 ({out.nbytes / 1e9:.1f} GB): "
        f"{time.perf_counter() - t0:.1f}s")
    return out


def phase_host(outdir):
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.checkpoint import (
        load_params_into_plan, save_model_sharded)

    assert len(jax.devices()) >= 8, "run with xla_force_host_platform_device_count=8"
    res = {"phase": "host", "vocab": V, "dim": D}
    vocab = build_vocab()
    mat = host_syn0()
    plan = make_mesh(1, 8)  # 8-way row sharding, the production embedding layout

    t0 = time.perf_counter()
    syn0 = jax.make_array_from_callback(
        (V, D), plan.embedding, lambda idx: mat[idx])
    jax.block_until_ready(syn0)
    res["place_on_mesh_s"] = round(time.perf_counter() - t0, 1)
    log(f"placed on (1, 8) mesh: {res['place_on_mesh_s']}s")

    ck = os.path.join(outdir, "ck10m")
    cfg = Word2VecConfig(vector_size=D, min_count=1, sharded_checkpoint=True)
    t0 = time.perf_counter()
    save_model_sharded(ck, vocab.words, vocab.counts, syn0, None, cfg)
    res["sharded_save_s"] = round(time.perf_counter() - t0, 1)
    sz = sum(os.path.getsize(os.path.join(r, f))
             for r, _, fs in os.walk(ck) for f in fs)
    res["checkpoint_gb"] = round(sz / 1e9, 2)
    log(f"row-shards save: {res['sharded_save_s']}s ({res['checkpoint_gb']} GB, "
        f"{sz / 1e9 / res['sharded_save_s']:.2f} GB/s)")

    del syn0  # free the placed device copy (7.7 GB) before the next step

    t0 = time.perf_counter()
    syn0_l, syn1_l = load_params_into_plan(ck, plan, V, D, dtype=jnp.bfloat16)
    jax.block_until_ready(syn0_l)
    res["streamed_load_s"] = round(time.perf_counter() - t0, 1)
    assert syn1_l is None
    log(f"streamed mmap load onto mesh: {res['streamed_load_s']}s")
    # spot-check a row survived the round trip
    np.testing.assert_array_equal(np.asarray(syn0_l[12345]), mat[12345])
    del syn0_l  # free before export (the first attempt OOM'd holding 3 copies)

    # NO host-phase find_synonyms: lax.top_k over 10M rows lowers to a per-row
    # sort on the CPU backend (measured: >30 min for 64 queries — killed); the
    # real number is the --phase device one, where top_k runs on the TPU.
    # Export streams from the HOST matrix (blockwise f32 convert) — the same
    # writer a real pod host runs after its PCIe fetch.
    model = Word2VecModel(vocab, mat, syn1=None, config=cfg)
    exp = os.path.join(outdir, "vectors_10m.bin")
    t0 = time.perf_counter()
    model.export_word2vec(exp, binary=True)
    res["export_binary_s"] = round(time.perf_counter() - t0, 1)
    res["export_gb"] = round(os.path.getsize(exp) / 1e9, 2)
    log(f"export_word2vec binary: {res['export_binary_s']}s "
        f"({res['export_gb']} GB, "
        f"{res['export_gb'] / res['export_binary_s']:.2f} GB/s)")
    with open(exp, "rb") as f:
        head = f.readline().split()
        assert int(head[0]) == V and int(head[1]) == D

    res["peak_rss_gb"] = round(peak_gb(), 1)
    print(json.dumps(res))


def phase_device(outdir):
    import jax
    import jax.numpy as jnp

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.models.word2vec import Word2VecModel

    dev = jax.devices()[0]
    log(f"device: {dev}")
    res = {"phase": "device", "vocab": V, "dim": D, "device": str(dev)}
    vocab = build_vocab()

    t0 = time.perf_counter()
    syn0 = jax.random.normal(jax.random.key(1), (V, D), jnp.bfloat16) * 0.1
    syn0.block_until_ready()
    log(f"syn0 on device [{V:,} x {D}] bf16 "
        f"({V * D * 2 / 1e9:.1f} GB HBM): {time.perf_counter() - t0:.1f}s")

    cfg = Word2VecConfig(vector_size=D, min_count=1)
    model = Word2VecModel(vocab, syn0, syn1=None, config=cfg)

    model.find_synonyms("w0", 10)  # compile + warm
    t0 = time.perf_counter()
    for i in range(5):
        model.find_synonyms(f"w{i + 1}", 10)
    res["find_synonyms_ms"] = round((time.perf_counter() - t0) / 5 * 1e3, 1)
    log(f"find_synonyms(top-10) over {V:,} rows: "
        f"{res['find_synonyms_ms']} ms/query (tunnel round-trip bound)")

    qs = [f"w{i * 991 + 3}" for i in range(128)]
    # warm at the SAME query-stack shape as the timed call — a different shape
    # would retrace and the timed dispatch would include the compile
    model.find_synonyms_batch(qs, 10, chunk=128)
    t0 = time.perf_counter()
    got = model.find_synonyms_batch(qs, 10, chunk=128)
    res["synonyms_batch128_ms_per_query"] = round(
        (time.perf_counter() - t0) / 128 * 1e3, 1)
    assert len(got) == 128
    log(f"find_synonyms_batch(128): "
        f"{res['synonyms_batch128_ms_per_query']} ms/query")

    # save/export refuse-note: a device->host pull of this matrix through the
    # measured ~9 MB/s tunnel is ~14 h — the IO ops are demonstrated in
    # --phase host on the same code path; on a co-located host the fetch is
    # PCIe-bound (estimate, labeled: ~0.5-2 s at 4-16 GB/s) + the host-phase
    # disk times
    res["save_export_note"] = (
        "run in --phase host: 7.7 GB device->host is infeasible through the "
        "9 MB/s remote tunnel (~14 h); co-located-host fetch is a PCIe-rate "
        "ESTIMATE, disk timings measured in the host phase")
    log(res["save_export_note"])
    print(json.dumps(res))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", choices=["host", "device"], required=True)
    ap.add_argument("--out", default="/tmp/model_ops_10m")
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)
    if args.phase == "host":
        phase_host(args.out)
    else:
        phase_device(args.out)


if __name__ == "__main__":
    main()
