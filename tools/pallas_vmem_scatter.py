"""Measure the in-VMEM scatter-apply rate — the last unmeasured number in the
Pallas-SGNS analysis (VERDICT r4 item 6).

The coalesced-DMA kernel shape the round-4 verdict asked about ("pool-resident
VMEM, batch-tiled, sorted segment updates, double-buffered DMA") decomposes
into three costs:

  1. getting update rows into VMEM        — free: they arrive as grid blocks
  2. getting TARGET rows in/out of VMEM   — the r3 measurement: ~0.25 us per
     row DMA issue, 10x the XLA emitter's 27 ns/row; only a CONTIGUOUS head
     block escapes this (one bulk DMA), which Zipf makes attractive (63% of
     update rows hit the top-2048 ids — PERF.md §3 probe)
  3. APPLYING updates row-by-row inside VMEM — measured HERE

If (3) alone is at or above the emitter's ~27 ns/row, a Pallas kernel cannot
beat the XLA scatter even with all data movement free, and the head-hybrid is
doubly dead (the §3 drop probe already showed the tail scatter still costs
full price). The kernel: update rows stream through VMEM as grid blocks, a
[H, D] head accumulator stays VMEM-resident across the grid, and a scalar
fori_loop applies each row to its target via dynamic VMEM addressing — exactly
the apply loop any coalesced-segment design bottoms out in.

Run: python tools/pallas_vmem_scatter.py [--h 2048] [--d 384] [--tile 1024]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--h", type=int, default=2048)
    ap.add_argument("--d", type=int, default=384)
    ap.add_argument("--b", type=int, default=65536)
    ap.add_argument("--tile", type=int, default=1024)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()
    H, D, B, T = args.h, args.d, args.b, args.tile
    assert B % T == 0

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from microbench import time_chunked

    print(f"device: {jax.devices()[0]}  H={H} D={D} B={B} tile={T}",
          file=sys.stderr)

    def kernel(idx_ref, x_ref, o_ref):
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            o_ref[...] = jnp.zeros_like(o_ref)

        def body(i, _):
            h = idx_ref[i]
            o_ref[pl.ds(h, 1), :] += x_ref[pl.ds(i, 1), :]
            return 0

        jax.lax.fori_loop(0, T, body, 0)

    @jax.jit
    def apply_updates(idx, x):
        return pl.pallas_call(
            kernel,
            grid=(B // T,),
            in_specs=[
                pl.BlockSpec((T,), lambda t: (t,), memory_space=pltpu.SMEM),
                pl.BlockSpec((T, D), lambda t: (t, 0)),
            ],
            out_specs=pl.BlockSpec((H, D), lambda t: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((H, D), jnp.float32),
        )(idx, x)

    rng = np.random.default_rng(0)
    # Zipf-hot indices into the head, like the production hot rows
    p = 1.0 / (np.arange(H) + 10.0) ** 1.07
    p /= p.sum()
    idxs = [jnp.asarray(rng.choice(H, size=B, p=p), jnp.int32)
            for _ in range(8)]
    x = jnp.asarray(rng.standard_normal((B, D), np.float32) * 1e-3)

    def step(carry, idx):
        out = apply_updates(idx, x)
        return carry + out[0, 0], out

    ts = []
    for _ in range(args.repeats):
        spc = time_chunked(
            step, lambda: jnp.float32(0.0),
            lambda i: (idxs[i % 8],),
            n_lo=2, n_hi=8,
            fetch=lambda c, out: c)
        ts.append(spc)
    med = float(np.median(ts))
    print(f"in-VMEM scatter-apply: {med * 1e3:7.3f} ms per {B} rows "
          f"-> {med / B * 1e9:6.1f} ns/row  "
          f"[{min(ts) / B * 1e9:.1f} .. {max(ts) / B * 1e9:.1f}]",
          file=sys.stderr)
    print(f"(XLA sorted-scatter emitter reference: ~27 ns/row, PERF.md §2)",
          file=sys.stderr)


if __name__ == "__main__":
    main()
