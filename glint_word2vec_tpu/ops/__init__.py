from glint_word2vec_tpu.ops.cbow_banded import cbow_step_banded_core
from glint_word2vec_tpu.ops.sampler import AliasTable, build_alias_table, sample_negatives
from glint_word2vec_tpu.ops.sgns import (
    init_embeddings,
    sgns_loss,
    sgns_step,
    sgns_step_shared,
    cbow_step,
    alpha_schedule,
)

__all__ = [
    "AliasTable",
    "build_alias_table",
    "sample_negatives",
    "init_embeddings",
    "sgns_loss",
    "sgns_step",
    "sgns_step_shared",
    "cbow_step",
    "cbow_step_banded_core",
    "alpha_schedule",
]
