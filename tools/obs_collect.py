#!/usr/bin/env python
"""Fleet observability collector CLI (docs/observability.md §9).

Merges N per-process telemetry artifacts — the router's sink, each
replica's sink (rotated segments included), trainer/ContinualRunner sinks,
and any ``.blackbox.json`` flight-recorder dumps — into ONE causally
ordered fleet timeline (obs/collect.py): cross-process clock alignment via
the wall/monotonic anchors every ``*_start`` record carries, trace
reassembly by ``trace_id``, publish chains by ``publish_sig``, and an
offline recompute of the availability/latency SLO burn (obs/slo.py — the
same math the live router's ``glint_serve_fleet_slo_*`` gauges use).

Outputs under ``--out``: ``timeline.perfetto.json`` (load in
https://ui.perfetto.dev or chrome://tracing) and ``fleet-summary.json``
(the same object as stdout, indented). Prints exactly ONE JSON line on
stdout (graftlint R7); progress goes to stderr.

Usage::

    python tools/obs_collect.py ARTIFACT [ARTIFACT ...]
        [--out DIR] [--slowest K] [--gate]
        [--slo-availability 0.999] [--slo-latency-ms 250]
        [--slo-latency-target 0.99]
        [--slo-window-short 300] [--slo-window-long 3600]

``ARTIFACT`` is a telemetry JSONL, a ``.blackbox.json`` dump, or a
directory to scan for both. ``--gate`` makes the exit code the incident
verdict: nonzero when any file fails schema validation, no records were
found, or any SLO burn window exceeds 1.0 — the CI fleet job runs the
fleet-kill drill's artifacts through exactly this.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("artifacts", nargs="+",
                    help="telemetry JSONLs, .blackbox.json dumps, or "
                         "directories holding them")
    ap.add_argument("--out", default="",
                    help="write timeline.perfetto.json + fleet-summary.json "
                         "here (default: no files, summary on stdout only)")
    ap.add_argument("--slowest", type=int, default=5,
                    help="how many slowest-query exemplar traces to keep")
    ap.add_argument("--gate", action="store_true",
                    help="exit nonzero on schema errors, zero records, or "
                         "any SLO burn window > 1.0 (the CI verdict mode)")
    ap.add_argument("--slo-availability", type=float, default=0.999)
    ap.add_argument("--slo-latency-ms", type=float, default=250.0)
    ap.add_argument("--slo-latency-target", type=float, default=0.99)
    ap.add_argument("--slo-window-short", type=float, default=300.0)
    ap.add_argument("--slo-window-long", type=float, default=3600.0)
    args = ap.parse_args()

    from glint_word2vec_tpu.obs.collect import (
        collect, export_perfetto, scan_artifacts)
    from glint_word2vec_tpu.obs.schema import (
        validate_blackbox_file, validate_file)
    from glint_word2vec_tpu.obs.slo import SloObjectives

    files = scan_artifacts(args.artifacts)
    log(f"[collect] {len(files)} artifact file(s)")

    # the schema validator IS part of the verdict: a merged timeline built
    # from drifted records would lie with confidence. A half-written FINAL
    # line is the one exception — that's what a SIGKILL mid-flush leaves,
    # the same torn tail the merge itself tolerates
    schema_errors: list = []
    torn_tails = 0
    for f in files:
        v = (validate_blackbox_file(f) if f.endswith(".blackbox.json")
             else validate_file(f, tolerate_torn_tail=True))
        torn_tails += int(bool(v.get("torn_tail")))
        if not v["ok"]:
            schema_errors.extend(v["errors"][:3])

    objectives = SloObjectives(
        availability=args.slo_availability,
        latency_ms=args.slo_latency_ms,
        latency_target=args.slo_latency_target,
        short_window_s=args.slo_window_short,
        long_window_s=args.slo_window_long)
    timeline, summary = collect(files, objectives, slowest=args.slowest)
    summary["schema_valid"] = not schema_errors
    summary["schema_errors"] = schema_errors[:5]
    summary["torn_tails"] = torn_tails
    summary["files"] = len(files)

    gated = bool(schema_errors) or summary["records"] == 0 or not (
        summary["slo"].get("within_budget", True))
    summary["ok"] = not gated

    if args.out:
        os.makedirs(args.out, exist_ok=True)
        perfetto = os.path.join(args.out, "timeline.perfetto.json")
        n = export_perfetto(timeline, perfetto)
        summary["perfetto"] = perfetto
        summary["perfetto_events"] = n
        with open(os.path.join(args.out, "fleet-summary.json"), "w",
                  encoding="utf-8") as f:
            json.dump(summary, f, indent=1)
        log(f"[collect] wrote {perfetto} ({n} events)")
    print(json.dumps(summary, allow_nan=False))
    if args.gate:
        return 1 if gated else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
