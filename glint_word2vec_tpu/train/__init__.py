from glint_word2vec_tpu.train.checkpoint import (
    CheckpointCorruptError,
    TrainState,
    load_latest_valid,
    load_model,
    save_model,
    verify_checkpoint,
)
from glint_word2vec_tpu.train.faults import NonFiniteParamsError
from glint_word2vec_tpu.train.trainer import HeartbeatRecord, Trainer

__all__ = [
    "CheckpointCorruptError",
    "NonFiniteParamsError",
    "TrainState",
    "load_latest_valid",
    "load_model",
    "save_model",
    "verify_checkpoint",
    "HeartbeatRecord",
    "Trainer",
]
