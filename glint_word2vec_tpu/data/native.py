"""ctypes binding for the native pair generator (``native/pairgen.cpp``).

The shared library is built on first use with ``g++`` (no Python headers, no
pybind11 — plain C ABI) and cached next to the source. Everything degrades
gracefully: if the toolchain or build is unavailable, :func:`native_available`
returns False and the pipeline stays on the bit-identical numpy path.

Set ``GLINT_DISABLE_NATIVE=1`` to force the numpy path (e.g. for A/B testing);
``GLINT_NATIVE_THREADS`` overrides the generator's thread count (default: up to 8,
capped by the host's cores).
"""

from __future__ import annotations

import ctypes
import glob
import logging
import os
import subprocess
import threading
import time
from typing import Optional, Tuple

import numpy as np
from glint_word2vec_tpu.lockcheck import make_lock

logger = logging.getLogger("glint_word2vec_tpu")

_ABI_VERSION = 1
_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "pairgen.cpp")
_LIB = os.path.join(os.path.dirname(_SRC), "libpairgen.so")

_lock = make_lock("data.native.load")
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def build_or_reload(src: str, lib_path: str, abi_symbol: str, abi_version: int,
                    std: str, what: str) -> Optional[ctypes.CDLL]:
    """The shared build-on-first-use contract for every native component:
    compile with g++ when the cached .so is missing or older than the source,
    load, verify the ABI symbol, and rebuild once on a stale/broken cache.
    Returns the CDLL or None (with a logged warning — callers fall back to
    their pure-Python path). Argtype configuration and caching stay with the
    calling module."""
    def build() -> bool:
        # per-process temp name: co-hosted builders (multi-process JAX workers,
        # parallel pytest) must not interleave g++ output into one file before
        # the atomic publish below
        tmp = f"{lib_path}.tmp.{os.getpid()}"
        # sweep temp objects orphaned by builders killed mid-compile (unique
        # names mean nothing ever overwrites them); only files older than the
        # build timeout — younger ones may belong to a live concurrent builder
        # glob.escape: a cache path containing [, ?, * must match literally —
        # unescaped it would silently sweep nothing (orphans accumulate) or
        # match unrelated files for deletion
        for stale in glob.glob(glob.escape(lib_path) + ".tmp*"):  # incl. legacy fixed ".tmp"
            try:
                if time.time() - os.path.getmtime(stale) > 300:
                    os.unlink(stale)
            except OSError:
                pass
        # _FILE_OFFSET_BITS=64: the ingest loader seeks with fseeko/off_t,
        # which is only 64-bit on ILP32 glibc with this macro
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", f"-std={std}",
               "-D_FILE_OFFSET_BITS=64", "-o", tmp, src]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError) as e:
            err = getattr(e, "stderr", b"") or b""
            logger.warning("native %s build failed (%s); using the Python "
                           "path. stderr: %s", what, e,
                           err.decode(errors="replace")[-500:])
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        os.replace(tmp, lib_path)
        return True

    needs_build = (not os.path.exists(lib_path)
                   or os.path.getmtime(lib_path) < os.path.getmtime(src))
    if needs_build and not build():
        return None
    try:
        lib = ctypes.CDLL(lib_path)
        if getattr(lib, abi_symbol)() != abi_version:
            raise OSError(f"stale {os.path.basename(lib_path)} ABI; rebuild")
    except OSError:
        # stale or broken cache: rebuild once
        if not build():
            return None
        lib = ctypes.CDLL(lib_path)
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("GLINT_DISABLE_NATIVE"):
            _load_failed = True
            return None
        lib = build_or_reload(_SRC, _LIB, "glint_pairgen_abi_version",
                              _ABI_VERSION, "c++17", "pairgen")
        if lib is None:
            _load_failed = True
            return None
        lib.glint_block_pairs.restype = ctypes.c_int64
        lib.glint_block_pairs.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,   # tokens, n_tokens
            ctypes.c_void_p, ctypes.c_int64,   # lengths, n_sents
            ctypes.c_void_p,                   # keep [V] f32
            ctypes.c_int32, ctypes.c_int32,    # window, legacy
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,  # seed, iter, shard
            ctypes.c_uint64,                   # token_base
            ctypes.c_int32,                    # n_threads
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,  # out c/x/clock
            ctypes.c_int64,                    # cap
            ctypes.c_void_p,                   # out_kept
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def default_threads() -> int:
    env = os.environ.get("GLINT_NATIVE_THREADS")
    if env:
        return max(1, int(env))
    return max(1, min(8, os.cpu_count() or 1))


def block_pairs_native(
    tokens: np.ndarray,
    lengths: np.ndarray,
    keep: np.ndarray,
    window: int,
    seed: int,
    iteration: int,
    shard: int,
    token_base: int,
    legacy_asymmetric_window: bool,
    n_threads: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Drop-in replacement for ``pipeline._block_pairs`` (same stream, bit-identical).

    The caller is the pipeline's producer thread; the C++ side fans out over
    sentence ranges and releases the GIL for the whole call (ctypes does).
    ``n_threads`` overrides :func:`default_threads` (0 = default) — the
    parallel slab producer divides the thread budget across its concurrent
    calls so pools never multiply (pipeline.epoch_batches). The emitted
    stream is deterministic at ANY thread count (ranges are position-keyed
    and written to disjoint output slices)."""
    lib = _load()
    assert lib is not None, "call native_available() first"
    N = int(tokens.shape[0])
    empty = (np.empty(0, np.int32), np.empty(0, np.int32), np.empty(0, np.int64), 0)
    if N == 0:
        return empty
    tokens = np.ascontiguousarray(tokens, dtype=np.int32)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    keep = np.ascontiguousarray(keep, dtype=np.float32)
    cap = N * max(2 * window - 2, 1)
    centers = np.empty(cap, np.int32)
    contexts = np.empty(cap, np.int32)
    clock = np.empty(cap, np.int64)
    kept = ctypes.c_int64(0)
    n = lib.glint_block_pairs(
        tokens.ctypes.data, N,
        lengths.ctypes.data, int(lengths.shape[0]),
        keep.ctypes.data,
        int(window), int(bool(legacy_asymmetric_window)),
        ctypes.c_uint32(seed & 0xFFFFFFFF), ctypes.c_uint32(iteration & 0xFFFFFFFF),
        ctypes.c_uint32(shard & 0xFFFFFFFF),
        ctypes.c_uint64(token_base),
        int(n_threads) if n_threads > 0 else default_threads(),
        centers.ctypes.data, contexts.ctypes.data, clock.ctypes.data,
        cap, ctypes.byref(kept))
    if n < 0:  # cannot happen under the documented cap bound; belt and braces
        raise RuntimeError("native pairgen capacity overflow")
    if n == 0:
        return (np.empty(0, np.int32), np.empty(0, np.int32),
                np.empty(0, np.int64), int(kept.value))
    return centers[:n], contexts[:n], clock[:n], int(kept.value)
