"""On-device unigram negative sampler (replaces reference component G7).

The reference materializes a server-resident unigram table of ``unigramTableSize`` entries
(default 10^8 — 400 MB of int32; mllib:81,234-244, built fork-side from broadcast vocab
counts, mllib:317,355-359) and draws negatives by indexing it with a shared seed so every
parameter-server shard samples identical negatives without communicating them (G3 contract,
mllib:419-421).

TPU-native replacement: a **Walker alias table** over the counts^0.75 unigram distribution —
O(2·vocab) memory instead of O(table_size), *exact* (no quantization), sampled fully
on-device with ``jax.random`` in O(1) per draw. The shared-seed trick survives as ordinary
functional PRNG: every device derives the same per-step key, so data-parallel replicas and
model shards agree on negatives for free.

A quantized table-based sampler (:func:`build_unigram_table`) is kept for distribution-parity
tests against the classic word2vec table semantics.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class AliasTable(NamedTuple):
    """Walker alias method tables for a categorical distribution over vocab rows.

    prob[i] ∈ [0,1]: probability of keeping bucket i's own index; alias[i]: the index drawn
    otherwise. Both shape [vocab_size]; small and replicable across the mesh.
    """

    prob: jax.Array   # float32 [V]
    alias: jax.Array  # int32 [V]

    @property
    def vocab_size(self) -> int:
        return self.prob.shape[0]


def build_alias_table(counts: np.ndarray, power: float = 0.75) -> AliasTable:
    """Build alias tables for p(w) ∝ counts[w]^power (classic word2vec 3/4 power).

    Host-side Vose construction, vectorized: each round pairs k small buckets with k
    distinct large buckets at once (every element is finalized exactly once, so total work
    is O(V) array ops across a handful of rounds — fast enough to rebuild at 10M vocab).
    """
    counts = np.asarray(counts, dtype=np.float64)
    if counts.ndim != 1 or counts.size == 0:
        raise ValueError("counts must be a nonempty 1-D array")
    weights = np.power(np.maximum(counts, 0.0), power)
    total = weights.sum()
    if total <= 0:
        raise ValueError("all counts are zero")
    V = counts.size
    scaled = weights * (V / total)  # mean 1.0
    prob = np.ones(V, dtype=np.float64)
    alias = np.arange(V, dtype=np.int64)

    small = np.flatnonzero(scaled < 1.0)
    large = np.flatnonzero(scaled >= 1.0)
    while small.size and large.size:
        k = min(small.size, large.size)
        s, small = small[:k], small[k:]
        l = large[:k]
        prob[s] = scaled[s]
        alias[s] = l
        scaled[l] -= 1.0 - scaled[s]
        now_small = l[scaled[l] < 1.0]
        large = np.concatenate([l[scaled[l] >= 1.0], large[k:]])
        small = np.concatenate([small, now_small])
    # leftovers are numerically ≈1: keep their own index
    prob[small] = 1.0
    prob[large] = 1.0
    return AliasTable(
        prob=jnp.asarray(prob, dtype=jnp.float32),
        alias=jnp.asarray(alias, dtype=jnp.int32),
    )


def sample_negatives(
    table: AliasTable, key: jax.Array, shape: Tuple[int, ...]
) -> jax.Array:
    """Draw negative word indices with p ∝ counts^power, fully on-device, any shape.

    Two uniforms per draw: bucket u1·V, then keep-vs-alias on u2 < prob[bucket].

    NOTE: uses ``jax.random`` (threefry). Fine for one-off draws, but inside a
    training program threefry ops cost ~2 ms per call on TPU — the hot path must use
    :func:`sample_negatives_hash` instead (see ops/prng.py for the measurements).
    """
    k1, k2 = jax.random.split(key)
    V = table.vocab_size
    buckets = jax.random.randint(k1, shape, 0, V, dtype=jnp.int32)
    u = jax.random.uniform(k2, shape, dtype=jnp.float32)
    keep = u < table.prob[buckets]
    return jnp.where(keep, buckets, table.alias[buckets])


def sample_negatives_hash(
    prob: jax.Array,    # [V] or [V, 1] float32 — pass as a jit ARGUMENT, not a closure
    alias: jax.Array,   # [V] or [V, 1] int32 — same
    seed,
    counter: jax.Array,
    shape: Tuple[int, ...],
) -> jax.Array:
    """Hot-path sampler: same alias-method draw as :func:`sample_negatives`, but from
    the counter-based hash PRNG (ops/prng.py) — deterministic in (seed, counter) and
    ~55x faster inside a jitted training step than the threefry path.

    The tables must be passed into the enclosing jit as arguments: closure-captured
    constants degrade the whole program on TPU (measured 3.4M → 204M pairs/s by this
    change plus the PRNG swap; see bench.py).
    """
    from glint_word2vec_tpu.ops.prng import randint_mod, uniform01

    V = prob.shape[0]
    prob2 = prob.reshape(V, 1)    # free view; (V, 1) row gathers take the fast path
    alias2 = alias.reshape(V, 1)
    buckets = randint_mod(seed, 0, counter, shape, V)
    u = uniform01(seed, 1, counter, shape)
    flat = buckets.reshape(-1)
    keep = u < prob2[flat][:, 0].reshape(shape)
    return jnp.where(keep, buckets, alias2[flat][:, 0].reshape(shape))


def sampled_probabilities(counts: np.ndarray, power: float = 0.75) -> np.ndarray:
    """Exact target distribution, for tests: p(w) = counts^power / Σ counts^power."""
    w = np.power(np.asarray(counts, dtype=np.float64), power)
    return w / w.sum()


def build_unigram_table(counts: np.ndarray, table_size: int, power: float = 0.75) -> np.ndarray:
    """Classic word2vec quantized unigram table (the reference's G7 semantics,
    unigramTableSize entries, mllib:81,234-244): entry j holds the word whose cumulative
    counts^power mass covers j/table_size. Kept for parity testing only — the alias sampler
    is exact and O(vocab)."""
    p = sampled_probabilities(counts, power)
    cdf = np.cumsum(p)
    grid = (np.arange(table_size, dtype=np.float64) + 0.5) / table_size
    return np.searchsorted(cdf, grid).astype(np.int32)
