"""R1 good: the fan-out routes through the blessed ordered-merge primitive."""
from glint_word2vec_tpu.data.pipeline import ordered_pool_map


def parallel_lengths(jobs, workers):
    return list(ordered_pool_map(len, jobs, workers=workers))
