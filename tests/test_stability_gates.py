"""Construction-time stability machinery (VERDICT r4 item 3).

Two measured divergence channels exist for large synchronous batches (EVAL.md):
the pool channel (auto-bounded by config's pool sizing since round 3) and the
duplicate-overload channel, which round 4 only WARNED about — the default
subsample 1e-3 at B=64k is a config the EVAL suite trained to NaN at 60M words.
These tests pin the round-5 behavior: an AUTO subsample ratio is lowered under
the measured boundary, an explicit unstable ratio is refused (with an
allow_unstable override), and the bench's headline gate matches the FULL
stability key (incl. subsample_ratio and logits_dtype) so it can no longer
bless the measured-NaN config.
"""

import os
import sys

import numpy as np
import pytest

# repo root (bench.py lives there, outside the package)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.train.trainer import Trainer


def _zipf_vocab(v: int = 20_000) -> Vocabulary:
    """A natural-language-shaped vocabulary: Zipf 1.05 counts, top word ~5% of
    tokens — the regime where B=64k batches overload the top word's row."""
    counts = np.maximum(1e8 / (np.arange(v) + 10.0) ** 1.05, 5.0).astype(np.int64)
    words = [f"w{i}" for i in range(v)]
    return Vocabulary.from_words_and_counts(words, counts)


BIG = dict(vector_size=64, pairs_per_batch=65536, min_count=5, seed=1)


class TestDuplicateChannelResolution:
    def test_explicit_unstable_ratio_refused(self):
        # the EVAL-divergent config: B=64k, subsample 1e-3, natural Zipf corpus
        vocab = _zipf_vocab()
        cfg = Word2VecConfig(subsample_ratio=1e-3, **BIG)
        with pytest.raises(ValueError, match="divergence boundary"):
            Trainer(cfg, vocab)

    def test_allow_unstable_overrides_refusal(self):
        vocab = _zipf_vocab()
        cfg = Word2VecConfig(subsample_ratio=1e-3, allow_unstable=True, **BIG)
        t = Trainer(cfg, vocab)  # constructs; fit-time warning still applies
        assert t.config.subsample_ratio == 1e-3  # never silently changed

    def test_auto_ratio_lowered_under_boundary(self):
        vocab = _zipf_vocab()
        cfg = Word2VecConfig(**BIG)  # subsample_ratio left AUTO
        t = Trainer(cfg, vocab)
        assert t.config.subsample_ratio < 1e-3
        assert t._duplicate_load(t.config.subsample_ratio) <= 300
        # close to the target, not needlessly aggressive (coverage costs quality)
        assert t._duplicate_load(t.config.subsample_ratio) > 150

    def test_stable_geometry_untouched(self):
        # small batches never hit the boundary: auto ratio stays at 1e-3
        vocab = _zipf_vocab()
        cfg = Word2VecConfig(vector_size=16, pairs_per_batch=256, min_count=5)
        t = Trainer(cfg, vocab)
        assert t.config.subsample_ratio == 1e-3

    def test_duplicate_scaling_bounds_by_construction(self):
        vocab = _zipf_vocab()
        cfg = Word2VecConfig(subsample_ratio=1e-3, duplicate_scaling=True, **BIG)
        t = Trainer(cfg, vocab)  # mean-update semantics: no refusal
        assert t.config.subsample_ratio == 1e-3

    def test_unboundable_corpus_refused_with_guidance(self):
        # tiny vocab on a LARGE corpus: the top word is ~1/3 of any full batch
        # no matter the subsampling (on a corpus smaller than a batch the
        # epoch-pair cap bounds the load instead, so big counts are needed to
        # keep batches full at strong subsampling) — must refuse with the
        # duplicate_scaling suggestion
        counts = np.array([10**9, 9 * 10**8, 8 * 10**8], np.int64)
        vocab = Vocabulary.from_words_and_counts(["a", "b", "c"], counts)
        cfg = Word2VecConfig(**BIG)
        with pytest.raises(ValueError, match="duplicate_scaling"):
            Trainer(cfg, vocab)

    def test_resolved_config_round_trips(self):
        # a checkpoint stores the RESOLVED ratio; reconstructing from it must
        # not refuse (it is inside the boundary) nor re-lower it
        vocab = _zipf_vocab()
        t = Trainer(Word2VecConfig(**BIG), vocab)
        resolved = t.config.subsample_ratio
        cfg2 = Word2VecConfig.from_dict(t.config.to_dict())
        t2 = Trainer(cfg2, vocab)
        assert t2.config.subsample_ratio == resolved

    def test_to_dict_preserves_auto_before_resolution(self):
        # a pre-resolution config shipped to a worker must stay AUTO there,
        # not read as an explicitly chosen 1e-3 and get refused
        cfg = Word2VecConfig(**BIG)
        cfg2 = Word2VecConfig.from_dict(cfg.to_dict())
        assert cfg2._auto_subsample
        t = Trainer(cfg2, _zipf_vocab())  # auto-lowers instead of refusing
        assert t.config.subsample_ratio < 1e-3

    def test_compat_layer_keeps_drop_in_behavior(self):
        # the compat surface mirrors the reference, which runs ANY of these
        # configs (async minibatches never face the synchronous duplicate
        # channel) — construction must warn, not refuse, even with its pinned
        # subsample_ratio=0.0 on a natural-language-shaped corpus
        from glint_word2vec_tpu.models.compat import ServerSideGlintWord2Vec
        cfg = (ServerSideGlintWord2Vec().setVectorSize(8).setMinCount(1)
               .to_config())
        assert cfg.allow_unstable and cfg.subsample_ratio == 0.0
        Trainer(cfg, _zipf_vocab())  # would refuse without the override

    def test_large_vocab_pool_advisory(self, caplog):
        # EVAL.md round-5 ladder: load 640 at 1.6M vocab measured a FINITE norm
        # blowup (no NaN); the trainer must advise growing the pool when a
        # large vocabulary meets a pool load in that region
        import logging
        counts = np.maximum(1e9 / (np.arange(600_000) + 10.0) ** 1.05, 5.0)
        vocab = Vocabulary.from_words_and_counts(
            [f"w{i}" for i in range(600_000)], counts.astype(np.int64))
        cfg = Word2VecConfig(vector_size=16, min_count=5, pairs_per_batch=65536,
                             negative_pool=512, subsample_ratio=1e-4)
        with caplog.at_level(logging.WARNING, logger="glint_word2vec_tpu"):
            caplog.clear()
            Trainer(cfg, vocab)
        assert any("large-vocab" in r.message for r in caplog.records)

    def test_replace_preserves_auto(self):
        cfg = Word2VecConfig(**BIG)
        assert cfg._auto_subsample
        cfg2 = cfg.replace(pairs_per_batch=1024)
        assert cfg2._auto_subsample and cfg2.subsample_ratio == 1e-3
        cfg3 = cfg.replace(subsample_ratio=5e-4)
        assert not cfg3._auto_subsample


class TestBenchHeadlineGate:
    """bench.eval_stable must match the full stability key (VERDICT r4 #3a)."""

    STABLE = {"pairs_per_batch": 65536, "negative_pool": 512,
              "param_dtype": "bfloat16", "logits_dtype": "bfloat16",
              "subsample_ratio": 1e-4, "corpus_words": 60_000_000}
    DIVERGED = {**STABLE, "subsample_ratio": 1e-3, "diverged": True}

    def _gate(self):
        import bench
        return bench.eval_stable

    def test_full_key_match_passes(self):
        gate = self._gate()
        assert gate([self.STABLE], 65536, 512, "bfloat16", "bfloat16", 1e-4)

    def test_subsample_mismatch_refused(self):
        # the r4 hole: EVAL holds a stable 1e-4 row AND a divergent 1e-3 row
        # with the same (batch, pool, dtype) key — the 1e-3 headline must NOT
        # be blessed by the 1e-4 evidence
        gate = self._gate()
        assert not gate([self.STABLE, self.DIVERGED],
                        65536, 512, "bfloat16", "bfloat16", 1e-3)

    def test_logits_dtype_mismatch_refused(self):
        gate = self._gate()
        assert not gate([self.STABLE], 65536, 512, "bfloat16", "float32", 1e-4)

    def test_diverged_and_rescored_rows_never_count(self):
        gate = self._gate()
        assert not gate([self.DIVERGED], 65536, 512, "bfloat16", "bfloat16", 1e-3)
        assert not gate([{**self.STABLE, "rescored": True}],
                        65536, 512, "bfloat16", "bfloat16", 1e-4)

    def test_short_run_insufficient(self):
        gate = self._gate()
        assert not gate([{**self.STABLE, "corpus_words": 17_000_000}],
                        65536, 512, "bfloat16", "bfloat16", 1e-4)
