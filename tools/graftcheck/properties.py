"""The executed property families (a)-(d) and the dispatch probe.

Every check returns ``None`` (holds) or a ``(key, message)`` pair — ``key`` is
a stable, digit-normalized identifier the shrinker minimizes against and the
baseline stores, ``message`` the human finding. Nothing here asserts: the
checker collects, shrinks, and gates.
"""

from __future__ import annotations

import json
import re
from typing import Dict, Optional, Tuple

Finding = Optional[Tuple[str, str]]

# Dispatch refusals that depend on the RUNTIME environment, not the config —
# the empirical twin of R8's "conditions referencing non-config state are
# exempt" rule. The probe environment (single-device plan passed explicitly,
# one process, uniform 1k-word vocabulary) is constructed so none of these
# can actually fire; the classifier stays anyway so a future probe-env change
# degrades to a classified record instead of a phantom parity violation.
_RUNTIME_REFUSALS = (
    r"needs \d+ devices",
    r"divisible by",
    r"multiple processes",
    r"process count",
    r"single-device plans only",
    r"most frequent word",            # corpus-dependent duplicate channel
    r"bounded by subsampling",
)


def normalize_message(msg: str, width: int = 90) -> str:
    """Stable refusal key: numerals and whitespace runs collapsed, clipped.
    Refusal messages embed the offending values ("got 0", "= 336"), which
    would make every signature unique; the template is the identity."""
    out = re.sub(r"-?\d+(?:\.\d+)?(?:e-?\d+)?", "#", msg)
    out = re.sub(r"\s+", " ", out).strip()
    return out[:width]


def is_runtime_refusal(msg: str) -> bool:
    return any(re.search(p, msg) for p in _RUNTIME_REFUSALS)


def construct(kwargs: Dict):
    """(config, None) on acceptance, (None, key) on a construction refusal.
    Any non-ValueError escaping __post_init__ is a finding in itself and is
    keyed with its exception type."""
    from glint_word2vec_tpu.config import Word2VecConfig
    try:
        return Word2VecConfig(**kwargs), None
    except ValueError as e:
        return None, "refused: " + normalize_message(str(e))
    except Exception as e:  # noqa: BLE001 — a non-ValueError IS the finding
        return None, f"crashed({type(e).__name__}): " + normalize_message(str(e))


def construction_key(kwargs: Dict) -> Optional[str]:
    """The shrinker predicate for construction refusals."""
    _, key = construct(kwargs)
    return key


# ---------------------------------------------------------------------------
# (b) serialization fixpoints
# ---------------------------------------------------------------------------

def check_serialization(cfg) -> Finding:
    from glint_word2vec_tpu.config import Word2VecConfig
    for markers in (True, False):
        tag = f"auto_markers={markers}"
        d1 = cfg.to_dict(auto_markers=markers)
        try:
            # the JSON hop is part of the contract: checkpoints/estimator
            # params travel as JSON, which turns mesh_shape into a list
            c2 = Word2VecConfig.from_dict(json.loads(json.dumps(d1)))
        except Exception as e:  # noqa: BLE001 — refusal or crash, same finding
            return (f"serial_fixpoint[{tag}]: from_dict refused its own "
                    f"to_dict output ({normalize_message(str(e), 60)})",
                    f"from_dict(to_dict(c, {tag})) raised "
                    f"{type(e).__name__}: {e}")
        d2 = c2.to_dict(auto_markers=markers)
        if d1 != d2:
            diff = {k: (d1[k], d2[k]) for k in d1 if d1[k] != d2.get(k)}
            return (f"serial_fixpoint[{tag}]: to_dict not a fixpoint under "
                    f"from_dict (fields {sorted(diff)})",
                    f"round trip changed {diff}")
        if markers:
            for flag in ("_auto_pool", "_auto_subsample"):
                if getattr(c2, flag, False) != getattr(cfg, flag, False):
                    return (f"serial_fixpoint[{tag}]: {flag} lost in the "
                            f"round trip",
                            f"{flag}: {getattr(cfg, flag, False)} -> "
                            f"{getattr(c2, flag, False)}")
            if c2 != cfg:
                return (f"serial_fixpoint[{tag}]: round-tripped config not "
                        f"equal to the original",
                        f"{c2} != {cfg}")
    return None


# ---------------------------------------------------------------------------
# (c) replace() re-resolution parity
# ---------------------------------------------------------------------------

# one flip per re-resolution input class: path switches (the PR-2 bug class),
# geometry changes (the AUTO pool rule's inputs), and a deliberately inert
# knob (seed — the flip that historically FROZE the resolved pool)
REPLACE_FLIPS = (
    ("cbow", True),
    ("use_pallas", True),
    ("step_lowering", "shard_map"),
    ("cbow_update", "banded"),
    ("duplicate_scaling", True),
    ("device_pairgen", True),
    ("pairs_per_batch", 256),
    ("pairs_per_batch", 8192),
    ("negatives", 15),
    ("vector_size", 64),
    ("seed", 123),
    ("subsample_ratio", 1e-4),
)


def check_replace(cfg, flips=REPLACE_FLIPS) -> Finding:
    from glint_word2vec_tpu.config import Word2VecConfig

    def outcome(thunk):
        try:
            c = thunk()
        except ValueError as e:
            return ("refused", normalize_message(str(e), 60))
        return ("ok", c.to_dict(auto_markers=True), c.to_dict(auto_markers=False),
                getattr(c, "_auto_pool", False),
                getattr(c, "_auto_subsample", False))

    base = cfg.to_dict(auto_markers=True)
    for knob, value in flips:
        if base.get(knob) == value:
            continue
        via_replace = outcome(lambda: cfg.replace(**{knob: value}))
        # the oracle is the CONSTRUCTOR, not from_dict: from_dict is
        # deliberately more lenient (it normalizes old-checkpoint dicts —
        # graftcheck's own first run caught this distinction when the
        # stored-pool normalization made from_dict accept a flip the
        # constructor and replace() both refuse)
        via_fresh = outcome(
            lambda: Word2VecConfig(**{**base, knob: value}))
        if via_replace != via_fresh:
            return (f"replace_parity[{knob}={value!r}]: replace() diverges "
                    f"from fresh construction",
                    f"replace -> {via_replace[:2]}, fresh -> {via_fresh[:2]}")
    return None


# ---------------------------------------------------------------------------
# (d) checkpoint-normalization monotonicity
# ---------------------------------------------------------------------------

def check_ckpt_normalization(cfg) -> Finding:
    from glint_word2vec_tpu.config import Word2VecConfig
    d = cfg.to_dict(auto_markers=False)
    # documented normalization 1: a pre-selection-matrix checkpoint stored a
    # RESOLVED auto pool beside cbow+duplicate_scaling (the old trainer
    # warn-ignored it); from_dict must normalize to 0, never refuse — a
    # refusal would brick the checkpoint
    if (d.get("cbow") and d.get("duplicate_scaling")
            and d.get("cbow_update", "scatter") == "scatter"):
        try:
            c2 = Word2VecConfig.from_dict({**d, "negative_pool": 64})
        except ValueError as e:
            return ("ckpt_norm[stored-pool]: old-checkpoint normalization "
                    "refused",
                    f"cbow+duplicate_scaling dict with stored pool raised: {e}")
        if c2.negative_pool != 0:
            return ("ckpt_norm[stored-pool]: stored pool not normalized to #",
                    f"negative_pool came back {c2.negative_pool}, expected 0")
    # documented normalization 2: unknown keys (newer writers) are filtered
    try:
        c3 = Word2VecConfig.from_dict({**d, "knob_from_the_future": 7})
    except Exception as e:  # noqa: BLE001 — refusal or crash, same finding
        return ("ckpt_norm[unknown-key]: unknown key not filtered",
                f"from_dict raised {type(e).__name__}: {e}")
    if c3 != cfg and c3.to_dict(False) != d:
        return ("ckpt_norm[unknown-key]: unknown key changed the config",
                "filtering a foreign key must be value-neutral")
    return None


# ---------------------------------------------------------------------------
# (a) dispatch parity — the probe
# ---------------------------------------------------------------------------

class DispatchProbe:
    """Builds REAL ``Trainer`` objects against a fixed hermetic environment:
    a uniform 1k-word vocabulary (the duplicate-overload channel's driving
    share is 1/V — it can never cross the refusal boundary) and an explicit
    single-device plan (no device-count or divisibility refusal can fire).
    Results are cached on the projection of the config onto the registry's
    non-inert knobs."""

    def __init__(self):
        import numpy as np
        from glint_word2vec_tpu.data.vocab import Vocabulary
        from glint_word2vec_tpu.parallel.mesh import make_mesh
        V = 1000
        self.vocab = Vocabulary.from_words_and_counts(
            [f"w{i}" for i in range(V)], np.full(V, 10, np.int64))
        self.plan = make_mesh(1, 1)
        self.cache: Dict[tuple, Optional[str]] = {}
        self.probes_run = 0

    @staticmethod
    def projection(kwargs: Dict) -> tuple:
        from tools.graftcheck.registry import KNOBS, config_defaults
        # fill defaults BEFORE projecting so a partial refusal-tier candidate
        # and a full-width pairwise row with the same effective config share
        # one cache entry (and one Trainer build)
        full = {**config_defaults(), **kwargs}
        return tuple(sorted(
            (k, repr(v)) for k, v in full.items()
            if k in KNOBS and not KNOBS[k].dispatch_inert))

    def probe_kwargs(self, kwargs: Dict) -> Optional[str]:
        """None = dispatch accepts; else the dispatch finding key. The
        shrinker predicate composes this with construction acceptance."""
        key = self.projection(kwargs)
        if key in self.cache:
            return self.cache[key]
        from glint_word2vec_tpu.config import Word2VecConfig
        from glint_word2vec_tpu.train.trainer import Trainer
        self.probes_run += 1
        result: Optional[str] = None
        try:
            Trainer(Word2VecConfig(**kwargs), self.vocab, plan=self.plan)
        except ValueError as e:
            kind = ("runtime_refusal" if is_runtime_refusal(str(e))
                    else "dispatch_refusal")
            result = f"{kind}: " + normalize_message(str(e))
        except Exception as e:  # noqa: BLE001 — a dispatch crash IS the finding
            result = f"dispatch_crash({type(e).__name__}): " + \
                normalize_message(str(e))
        self.cache[key] = result
        return result
