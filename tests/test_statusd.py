"""Live-inspection endpoint suite (docs/observability.md §7): the server
really serves JSON + Prometheus + healthz DURING a fit, is fit-scoped
(stopped at run end), read-only (404 elsewhere, GET only), and — the
acceptance contract — ZERO-cost when off: no thread created, no socket
bound, no phase accumulator armed."""

import json
import socket
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.obs.statusd import StatusServer, prometheus_text
from glint_word2vec_tpu.train.trainer import Trainer


def _toy_trainer(seed=0, n=250, **cfg_kw):
    rng = np.random.default_rng(seed)
    sents = [[f"w{i}" for i in rng.integers(0, 30, 20)] for _ in range(n)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=8, pairs_per_batch=128, window=3,
                         num_iterations=2, steps_per_dispatch=2,
                         heartbeat_every_steps=2, subsample_ratio=0.0,
                         prefetch_chunks=0, seed=1, **cfg_kw)
    return Trainer(cfg, vocab), encode_sentences(sents, vocab, 1000)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port, path, timeout=5):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout)


# -- unit: the server + prometheus rendering -------------------------------------------


def test_server_routes_and_readonly():
    snap = {"global_step": 7, "status": "running", "pairs_per_sec": 123.0,
            "lr_scale": 0.5, "recoveries": 1,
            "norms": {"syn0": {"max_norm": 2.5, "frac_over": 0.0}},
            "phases": {"dispatch": {"count": 3, "total_s": 0.5,
                                    "p99_s": 0.2}}}
    srv = StatusServer(0, lambda: snap).start()  # ephemeral port (unit only;
    try:                                         # config refuses 0 as "on")
        port = srv.port
        assert json.load(_get(port, "/status.json"))["global_step"] == 7
        assert json.load(_get(port, "/"))["status"] == "running"
        assert _get(port, "/healthz").read() == b"ok\n"
        text = _get(port, "/metrics").read().decode()
        assert "glint_global_step 7" in text
        assert 'glint_norm_max_norm{matrix="syn0"} 2.5' in text
        assert 'glint_phase_seconds_total{phase="dispatch"} 0.5' in text
        assert "glint_running 1" in text
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(port, "/shutdown")
        assert e.value.code == 404
    finally:
        srv.stop()
    # stopped: the port no longer answers
    with pytest.raises((ConnectionRefusedError, urllib.error.URLError)):
        _get(port, "/healthz", timeout=1)


def test_prometheus_text_skips_missing_and_none():
    text = prometheus_text({"global_step": 1, "pairs_per_sec": None,
                            "status": "idle"})
    assert "glint_global_step 1" in text
    assert "pairs_per_sec" not in text
    assert "glint_running 0" in text


# -- trainer integration ---------------------------------------------------------------


def test_serves_during_fit_and_stops_after(tmp_path):
    port = _free_port()
    trainer, enc = _toy_trainer(
        telemetry_path=str(tmp_path / "run.jsonl"), status_port=port)
    seen = {}

    def on_hb(rec):
        if seen:
            return
        seen["snap"] = json.load(_get(port, "/status.json"))
        seen["metrics"] = _get(port, "/metrics").read().decode()

    trainer.fit(enc, on_heartbeat=on_hb)
    snap = seen["snap"]
    assert snap["status"] == "running"
    assert snap["global_step"] >= 2
    assert snap["run_id"]
    assert snap["norms"]["syn0"]["max_norm"] > 0
    assert "glint_pairs_per_sec" in seen["metrics"]
    assert 'glint_phase_seconds_total{phase="dispatch"}' in seen["metrics"]
    # fit-scoped: the endpoint is gone once the run ended
    assert trainer._statusd is None
    with pytest.raises((ConnectionRefusedError, urllib.error.URLError)):
        _get(port, "/healthz", timeout=1)
    # the snapshot is still callable offline (idle state)
    assert trainer.status_snapshot()["status"] == "idle"


def test_status_without_telemetry_arms_phases(tmp_path):
    """status_port alone (no sink) must still attribute time — the endpoint
    serves phases without requiring a run log on disk."""
    port = _free_port()
    trainer, enc = _toy_trainer(n=60, status_port=port)
    trainer.fit(enc)
    assert trainer._phases.enabled
    assert trainer.status_snapshot()["phases"]["dispatch"]["count"] > 0


def test_zero_cost_when_off():
    """The acceptance contract: status_port=0 (default) creates NO thread
    and binds NO socket."""
    before = {t.name for t in threading.enumerate()}
    trainer, enc = _toy_trainer(n=60)
    trainer.fit(enc)
    after = {t.name for t in threading.enumerate()}
    assert trainer._statusd is None
    assert not any("statusd" in name for name in after - before)


def test_status_port_validation():
    with pytest.raises(ValueError, match="status_port"):
        Word2VecConfig(status_port=-1)
    with pytest.raises(ValueError, match="status_port"):
        Word2VecConfig(status_port=70000)
    with pytest.raises(ValueError, match="blackbox_ring"):
        Word2VecConfig(blackbox_ring=0)
