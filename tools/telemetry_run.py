#!/usr/bin/env python
"""Scripted telemetry fit: the acceptance driver for the observability layer
(docs/observability.md) and the CI artifact producer.

Runs a toy-corpus fit with telemetry ON (JSONL sink + host trace spans +
norm watchdog armed) through the production Trainer, then:

1. validates every emitted JSONL record against the schema catalogue
   (obs/schema.py — the drift gate CI fails on);
2. checks the exported Chrome-trace file parses and carries the
   producer/stage/dispatch/probe/checkpoint spans;
3. (``--overhead``) measures telemetry cost: interleaved fits with telemetry
   off/on (3 trials each, median pairs/s) — the acceptance bar is < 2%
   regression at heartbeat cadence.

Artifacts land under ``--out`` (``run.jsonl`` + ``run.jsonl.trace.json``) so
the CI job can upload them. Prints exactly ONE JSON line on stdout (the R7
driver-tool contract); progress goes to stderr.

Usage::

    python tools/telemetry_run.py --out /tmp/telemetry [--smoke] [--overhead]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

# span names the scripted fit must produce (the acceptance list from ISSUE 6;
# "producer" covers the feed producer, "stage_put" the staging path,
# "health_probe" the fused probe, "checkpoint_save" the save path)
REQUIRED_SPANS = ("producer", "stage_put", "dispatch", "health_probe",
                  "checkpoint_save")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def toy_sentences(n_sentences: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return [[f"w{i}" for i in rng.integers(0, 50, 20)]
            for _ in range(n_sentences)]


def _build(sentences, **cfg_kw):
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer
    vocab = build_vocab(sentences, min_count=1)
    enc = encode_sentences(sentences, vocab, 1000)
    cfg = Word2VecConfig(
        vector_size=16, pairs_per_batch=512, window=3, num_iterations=2,
        steps_per_dispatch=4, heartbeat_every_steps=8, subsample_ratio=0.0,
        seed=1, **cfg_kw)
    return Trainer(cfg, vocab), enc


def scripted_fit(out_dir: str, n_sentences: int) -> dict:
    """One telemetry-on fit; returns the artifact summary (validated)."""
    from glint_word2vec_tpu.obs.schema import validate_file
    run_log = os.path.join(out_dir, "run.jsonl")
    trainer, enc = _build(
        toy_sentences(n_sentences), telemetry_path=run_log,
        norm_watch="warn")
    trainer.fit(enc, checkpoint_path=os.path.join(out_dir, "ck"),
                checkpoint_every_steps=16)
    trace_path = run_log + ".trace.json"

    summary = validate_file(run_log)
    spans: list = []
    trace_ok = False
    try:
        with open(trace_path) as f:
            doc = json.load(f)
        spans = sorted({e["name"] for e in doc.get("traceEvents", [])
                        if e.get("ph") == "X"})
        trace_ok = True
    except (OSError, json.JSONDecodeError, KeyError) as e:
        summary["errors"] = summary.get("errors", []) + [f"trace: {e}"]
    missing = [s for s in REQUIRED_SPANS if s not in spans]
    # a CLEAN run must leave no flight-recorder dump — the blackbox is a
    # death artifact (obs/blackbox.py); chaos_run's `blackbox` phase proves
    # the dying-run half
    blackbox_absent = not os.path.exists(run_log + ".blackbox.json")
    ok = bool(summary["ok"] and trace_ok and not missing and blackbox_absent
              and summary["kinds"].get("run_start") == 1
              and summary["kinds"].get("run_end") == 1
              and summary["kinds"].get("heartbeat", 0) >= 1)
    return {
        "ok": ok,
        "blackbox_absent": blackbox_absent,
        "run_log": run_log,
        "trace": trace_path,
        "records": summary["records"],
        "kinds": summary["kinds"],
        "schema_valid": summary["ok"],
        "schema_errors": summary.get("errors", [])[:5],
        "spans": spans,
        "missing_spans": missing,
        "steps": int(trainer.global_step),
        "heartbeats_in_ring": len(trainer.heartbeats),
    }


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def measure_overhead(n_sentences: int, trials: int = 4,
                     workdir: str = "", status: bool = False) -> dict:
    """Interleaved telemetry-off/on A/B at heartbeat cadence (the PERF.md §3
    interleaving methodology), with two noise defenses this container made
    necessary: (1) ALTERNATING arm order per trial — a fixed off-then-on
    order measured a phantom 5% "overhead" that was pure host drift (the
    first fit of each pair ran hotter); (2) steady-state scoring. Geometry is
    production-PROPORTIONED, not toy: multi-ms dispatch chunks at a 16-step
    cadence (6x more frequent than the production default of 100) — probing
    a microsecond-step toy fit every 2 steps measures the probe's fixed
    cost, not the heartbeat-cadence overhead the acceptance bar is about.
    Importable — bench.py --smoke prints this measurement as its JSON line.

    ``status=True``: the on arm ADDITIONALLY serves the live status endpoint
    (config.status_port, obs/statusd.py) and each on-trial scrapes
    /status.json + /metrics once mid-fit from the heartbeat callback — so
    the measured arm is a REALLY-serving endpoint, not an idle socket. Same
    < 2% acceptance bar (docs/observability.md)."""
    workdir = workdir or tempfile.mkdtemp(prefix="glint_obs_bench_")
    # floor the corpus so every fit spans >= ~10 heartbeat windows — the
    # steady-state scoring below needs windows to drop and windows to keep
    n_sentences = max(n_sentences, 3000)
    rng = np.random.default_rng(4)
    sents = [[f"w{i}" for i in rng.integers(0, 2000, 30)]
             for _ in range(n_sentences)]
    geom = dict(vector_size=64, pairs_per_batch=4096, window=3,
                num_iterations=6, steps_per_dispatch=8,
                heartbeat_every_steps=16, subsample_ratio=0.0, seed=1)

    def build(**kw):
        from glint_word2vec_tpu.config import Word2VecConfig
        from glint_word2vec_tpu.data.pipeline import encode_sentences
        from glint_word2vec_tpu.data.vocab import build_vocab
        from glint_word2vec_tpu.train.trainer import Trainer
        vocab = build_vocab(sents, min_count=1)
        return (Trainer(Word2VecConfig(**geom, **kw), vocab),
                encode_sentences(sents, vocab, 1000))

    # steady-state scoring: each heartbeat already reports pairs/s over its
    # own window (probe + sink cost INCLUDED in the on-arm windows, since the
    # probe runs before the heartbeat clock is read); the first windows carry
    # the jit compile and are dropped. Whole-fit wall clock would fold 1-2 s
    # of compile into a ~5 s fit and swamp a 2% bar with compile-time noise.
    warmup = 2
    samples = {"off": [], "on": []}
    scrapes = 0
    for trial in range(trials):
        arms = ("off", "on") if trial % 2 == 0 else ("on", "off")
        for arm in arms:
            kw = {}
            on_heartbeat = None
            if arm == "on":
                kw = dict(telemetry_path=os.path.join(
                    workdir, f"run_{trial}.jsonl"), norm_watch="warn")
                if status:
                    port = _free_port()
                    kw["status_port"] = port
                    scraped = []

                    def on_heartbeat(rec, _port=port, _s=scraped):
                        if _s:
                            return
                        import urllib.request
                        snap = json.load(urllib.request.urlopen(
                            f"http://127.0.0.1:{_port}/status.json",
                            timeout=5))
                        urllib.request.urlopen(
                            f"http://127.0.0.1:{_port}/metrics",
                            timeout=5).read()
                        assert snap["status"] == "running", snap
                        _s.append(True)
            trainer, enc = build(**kw)
            trainer.fit(enc, on_heartbeat=on_heartbeat)
            if arm == "on" and status:
                scrapes += len(scraped)
            window_pps = [hb.pairs_per_sec
                          for hb in trainer.heartbeats][warmup:]
            samples[arm].extend(window_pps)
            log(f"overhead trial {trial} {arm}: "
                f"{np.median(window_pps):,.0f} pairs/s "
                f"({len(window_pps)} windows)")
    off = float(np.median(samples["off"]))
    on = float(np.median(samples["on"]))
    spread = float(np.percentile(samples["off"], 75)
                   / max(np.percentile(samples["off"], 25), 1e-9) - 1.0)
    if status:
        assert scrapes == trials, (
            f"status arm scraped {scrapes}/{trials} fits — the endpoint "
            f"was not live during every on-trial")
    return {
        **({"status_arm": True, "status_scrapes": scrapes}
           if status else {}),
        "telemetry_off_pairs_per_sec": round(off, 1),
        "telemetry_on_pairs_per_sec": round(on, 1),
        # signed: a negative value means the on-arm measured FASTER, i.e. the
        # true overhead is below this host's noise floor (see window_iqr_frac)
        "telemetry_overhead_frac": round(1.0 - on / off, 4),
        "window_iqr_frac": round(spread, 4),
        "trials": trials,
        "basis": ("median steady-state heartbeat-window pairs/s, "
                  f"{warmup} warmup windows dropped, arm order alternated "
                  "per trial"),
        "windows_per_arm": len(samples["off"]),
    }


def measure_trace_overhead(trials: int = 4, queries: int = 400,
                           workdir: str = "") -> dict:
    """The ISSUE-13 zero-cost acceptance A/B: an in-process 2-replica
    fleet (ReplicaSet.adopt — no subprocess noise) serving one in-memory
    model, queried back-to-back with tracing OFF (no sinks anywhere: the
    router allocates no trace context, requests cross the submit path
    byte-identical to the pre-trace protocol) vs ON (router + replica
    sinks, every query emitting its full 5-span breakdown). Interleaved
    trials with alternating arm order and median-of-QPS scoring — the
    same drift defenses as :func:`measure_overhead`. The batcher runs at
    ``max_delay_ms=0`` so the measured path is the submit/dispatch hot
    path, not the coalescing timer."""
    import time as _time

    import jax.numpy as jnp

    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.serve.fleet import FleetRouter, ReplicaSet
    from glint_word2vec_tpu.serve.service import EmbeddingService

    workdir = workdir or tempfile.mkdtemp(prefix="glint_trace_bench_")
    os.makedirs(workdir, exist_ok=True)
    v, d = 512, 32
    rng = np.random.default_rng(7)
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(v)], np.ones(v, np.int64))
    model = Word2VecModel(vocab, jnp.asarray(
        rng.standard_normal((v, d)).astype(np.float32)))
    samples = {"off": [], "on": [], "sampled": []}
    for trial in range(trials):
        order = ("off", "on", "sampled")
        arms = order if trial % 2 == 0 else order[::-1]
        for arm in arms:
            def p(name):
                return (os.path.join(workdir, f"t{trial}_{name}.jsonl")
                        if arm != "off" else "")
            svcs = [EmbeddingService(model=model, ann=False,
                                     max_delay_ms=0.0,
                                     telemetry_path=p(f"{arm}_r{i}"),
                                     process_name=f"r{i}")
                    for i in range(2)]
            router = FleetRouter(ReplicaSet.adopt(svcs), probe_s=30.0,
                                 hedge_ms=0.0, retry_deadline_s=10.0,
                                 telemetry_path=p(f"{arm}_router"),
                                 trace_sample=16 if arm == "sampled" else 1)
            try:
                for i in range(32):  # warm the dispatch path
                    router.synonyms(f"w{i}", 5)
                t0 = _time.perf_counter()
                for i in range(queries):
                    router.synonyms(f"w{i % v}", 5)
                dt = _time.perf_counter() - t0
            finally:
                router.close()
            samples[arm].append(queries / dt)
            log(f"trace-overhead trial {trial} {arm}: "
                f"{queries / dt:,.0f} q/s")
    off = float(np.median(samples["off"]))
    on = float(np.median(samples["on"]))
    sampled = float(np.median(samples["sampled"]))
    return {
        "tracing_off_qps": round(off, 1),
        "tracing_on_qps": round(on, 1),
        "tracing_sampled_16_qps": round(sampled, 1),
        # the off arm IS the zero-cost claim: no sink → no trace context
        # born at submit (fleet._request), no span ids, no clock reads —
        # these measure what tracing costs when you TURN IT ON (signed;
        # negative = below this host's noise floor). The on-arm cost is
        # ~5 flushed sink writes per query, which toy-latency queries
        # make look enormous — trace_sample=16 is the production lever
        # (docs/observability.md §9).
        "tracing_on_overhead_frac": round(1.0 - on / off, 4),
        "tracing_sampled_16_overhead_frac": round(1.0 - sampled / off, 4),
        "trials": trials,
        "queries_per_arm_per_trial": queries,
        "basis": ("median q/s over interleaved off/on/sampled trials, arm "
                  "order alternated, in-process 2-replica fleet, "
                  "max_delay_ms=0"),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--out", default="",
                    help="artifact directory (default: a fresh temp dir)")
    ap.add_argument("--smoke", action="store_true",
                    help="small corpus / fast (tier-1 + CI)")
    ap.add_argument("--overhead", action="store_true",
                    help="also run the interleaved telemetry-off/on "
                         "throughput A/B")
    ap.add_argument("--status-overhead", action="store_true",
                    help="overhead A/B with the live status endpoint "
                         "SERVING (and scraped mid-fit) on the on arm — "
                         "the obs/statusd.py acceptance measurement")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="fleet trace-propagation off/on A/B (ISSUE 13 "
                         "zero-cost-when-off acceptance; obs/trace.py)")
    args = ap.parse_args()

    out_dir = args.out or tempfile.mkdtemp(prefix="glint_telemetry_")
    os.makedirs(out_dir, exist_ok=True)
    n = 300 if args.smoke else 1500

    log(f"telemetry_run: scripted fit -> {out_dir}")
    result = scripted_fit(out_dir, n)
    if args.overhead:
        result["overhead"] = measure_overhead(
            n, workdir=os.path.join(out_dir, "bench"))
    if args.status_overhead:
        result["status_overhead"] = measure_overhead(
            n, workdir=os.path.join(out_dir, "bench_status"), status=True)
    if args.trace_overhead:
        result["trace_overhead"] = measure_trace_overhead(
            trials=3 if args.smoke else 4,
            queries=200 if args.smoke else 400,
            workdir=os.path.join(out_dir, "bench_trace"))
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
