"""R11 good twin: every whole-collection access of the shared ring holds
the same lock, and the one lock-free consumer goes through a documented
snapshot helper (copy under the lock, sort outside)."""
import collections
import threading


class LatencyRing:
    def __init__(self):
        self._lock = threading.Lock()
        self._latencies = collections.deque(maxlen=512)
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            with self._lock:
                self._latencies.append(0.0)

    def snapshot_latencies(self):
        """Copy under the lock so callers may sort/iterate freely — the
        deque itself is never exposed to a second thread."""
        with self._lock:
            return list(self._latencies)

    def stats(self):
        lats = self.snapshot_latencies()
        lats.sort()
        return lats
