"""R7 bad: human chatter on stdout + a second JSON line."""
import json


def main():
    print("starting benchmark")
    result = {"ok": True}
    print(json.dumps(result))
    print(json.dumps({"extra": 1}))
