"""Fixture lock registry for the R10 handler-safety pair."""
import threading

LOCK_TABLE = {
    "ring": {"rank": 10, "kind": "lock",
             "site": "glint_word2vec_tpu/svc.py:Recorder.__init__",
             "owner": "fixture recorder"},
}


def make_lock(name):
    return threading.Lock()
