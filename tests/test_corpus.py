"""Streaming-ingestion tests (data/corpus.py): the bounded-RAM analog of the
reference's unbounded RDD input (mllib:310-345)."""

import numpy as np
import pytest

from glint_word2vec_tpu.data.corpus import (
    EncodedCorpus,
    TokenFileCorpus,
    encode_corpus,
)
from glint_word2vec_tpu.data.pipeline import encode_sentences, epoch_batches
from glint_word2vec_tpu.data.vocab import build_vocab


@pytest.fixture()
def corpus_file(tmp_path):
    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(200)]
    lines = []
    for _ in range(400):
        n = rng.integers(1, 60)
        lines.append(" ".join(words[j] for j in rng.integers(0, 200, n)))
    lines.append("")          # blank line: skipped
    lines.append("oovonly1 oovonly2")  # all-OOV after min_count: dropped on encode
    p = tmp_path / "corpus.txt"
    p.write_text("\n".join(lines), encoding="utf-8")
    return str(p)


def test_token_file_corpus_is_reiterable(corpus_file):
    c = TokenFileCorpus(corpus_file)
    first = sum(1 for _ in c)
    second = sum(1 for _ in c)
    assert first == second == 401  # blank line dropped, oov line still tokenized


def test_encode_corpus_matches_in_memory_encoding(corpus_file, tmp_path):
    c = TokenFileCorpus(corpus_file)
    vocab = build_vocab(c, min_count=2)
    want = encode_sentences(list(c), vocab, max_sentence_length=37)
    got = encode_corpus(c, vocab, str(tmp_path / "enc"), max_sentence_length=37)
    assert len(got) == len(want)
    assert got.total_tokens == sum(len(s) for s in want)
    for i in (0, 1, len(want) // 2, len(want) - 1):
        np.testing.assert_array_equal(got[i], want[i])
    # reopen from disk
    re = EncodedCorpus(str(tmp_path / "enc"))
    assert len(re) == len(want)
    np.testing.assert_array_equal(re[3], want[3])


def test_epoch_batches_identical_from_mmap_and_list(corpus_file, tmp_path):
    """The trainer consumes batches; disk-backed and in-RAM sentences must produce
    bit-identical streams (same seed → same training run)."""
    c = TokenFileCorpus(corpus_file)
    vocab = build_vocab(c, min_count=2)
    in_ram = encode_sentences(list(c), vocab, max_sentence_length=100)
    on_disk = encode_corpus(c, vocab, str(tmp_path / "enc2"), max_sentence_length=100)
    kw = dict(pairs_per_batch=512, window=3, subsample_ratio=1e-3, seed=5, iteration=1)
    for a, b in zip(epoch_batches(in_ram, vocab, **kw),
                    epoch_batches(on_disk, vocab, **kw)):
        np.testing.assert_array_equal(a.centers, b.centers)
        np.testing.assert_array_equal(a.contexts, b.contexts)
        np.testing.assert_array_equal(a.mask, b.mask)


def test_fit_from_file_corpus(corpus_file, tmp_path):
    """End-to-end: Word2Vec.fit streams a TokenFileCorpus through a disk cache."""
    from glint_word2vec_tpu.models.estimator import Word2Vec

    model = Word2Vec(vector_size=16, min_count=2, pairs_per_batch=256,
                     num_iterations=1, window=3, seed=1).fit(
        TokenFileCorpus(corpus_file),
        encode_cache_dir=str(tmp_path / "cache"))
    assert model.vector_size == 16
    v = model.transform("w0")
    assert v.shape == (16,) and np.isfinite(v).all()
    # the cache dir holds the encoded shards
    assert (tmp_path / "cache" / "tokens.bin").exists()


def test_streaming_resume_reuses_cache(corpus_file, tmp_path):
    """Word2Vec.resume accepts encode_cache_dir and reuses an existing encoded corpus
    without a re-encoding pass (VERDICT r2 #8: long-run resume must compose with the
    long-run ingestion path)."""
    from glint_word2vec_tpu.models.estimator import Word2Vec

    cache = str(tmp_path / "cache")
    ckpt = str(tmp_path / "ckpt")
    Word2Vec(vector_size=16, min_count=2, pairs_per_batch=256,
             num_iterations=2, window=3, seed=1).fit(
        TokenFileCorpus(corpus_file), encode_cache_dir=cache,
        checkpoint_path=ckpt, checkpoint_every_steps=1)
    mtime = (tmp_path / "cache" / "tokens.bin").stat().st_mtime_ns
    resumed = Word2Vec.resume(ckpt, TokenFileCorpus(corpus_file),
                              encode_cache_dir=cache)
    assert resumed.train_state.finished
    # the encoded corpus was reused, not rewritten
    assert (tmp_path / "cache" / "tokens.bin").stat().st_mtime_ns == mtime


def test_resume_rejects_foreign_cache(corpus_file, tmp_path):
    """A cache encoded under a different vocabulary must be rejected, not silently
    trained on (ids would map to the wrong words)."""
    import pytest
    from glint_word2vec_tpu.models.estimator import Word2Vec

    ckpt = str(tmp_path / "ckpt")
    Word2Vec(vector_size=16, min_count=2, pairs_per_batch=256,
             num_iterations=2, window=3, seed=1).fit(
        TokenFileCorpus(corpus_file),
        checkpoint_path=ckpt, checkpoint_every_steps=1)
    # a cache built under a DIFFERENT vocab (min_count=1 changes the word set)
    other = build_vocab(TokenFileCorpus(corpus_file), min_count=1)
    foreign = str(tmp_path / "foreign")
    encode_corpus(TokenFileCorpus(corpus_file), other, foreign)
    with pytest.raises(ValueError, match="different vocabulary"):
        Word2Vec.resume(ckpt, TokenFileCorpus(corpus_file),
                        encode_cache_dir=foreign)
