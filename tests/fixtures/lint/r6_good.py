"""R6 good: placement goes through put_global / the allowlisted stager."""
import jax

from glint_word2vec_tpu.parallel.distributed import put_global


class Trainer:
    def _stage_to_device(self, chunks):  # the allowlisted owner
        for chunk in chunks:
            chunk["arrays"] = {
                k: jax.device_put(v) for k, v in chunk["arrays"].items()}
            yield chunk

    def _fit(self, shardings, arrays):
        return put_global(shardings, arrays)
