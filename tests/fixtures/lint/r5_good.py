"""R5 good: reads retry with backoff; the write pass is exempt by design
(one-shot encode must restart from scratch, never blind-retry)."""
import numpy as np

from glint_word2vec_tpu.train.faults import retry_io


def load(path):
    def _open():
        return open(path, "r", encoding="utf-8")

    with retry_io(_open, what="open meta") as f:
        meta = f.read()
    tokens = retry_io(
        lambda: np.memmap(path + ".bin", dtype=np.int32, mode="r"),
        what="mmap tokens")
    return meta, tokens


def write(path, text):
    with open(path, "w", encoding="utf-8") as f:  # write: exempt
        f.write(text)
