"""Mode-B deployment test: a SECOND process loads a checkpoint and serves model ops
while the writer process is still alive — the analog of the reference's
separate-PS-cluster mode (README.md:45-57; it spec:108-135,157-196), where query
clients attach to PS state owned by another application.

Covers: dense checkpoint serving, row-shards checkpoint serving onto the server's own
mesh (streamed, no dense host copy), and the reload op picking up a newer checkpoint
written by the trainer after the server started."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.train.trainer import Trainer

SERVE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "serve_checkpoint.py")


def _corpus(n=150, v=60, seed=2):
    rng = np.random.default_rng(seed)
    words = [f"w{i}" for i in range(v)]
    return [[words[j] for j in rng.integers(0, v, 12)] for _ in range(n)]


class _Server:
    def __init__(self, path, mesh=None, errfile=None):
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS",)}
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        repo = os.path.dirname(os.path.dirname(SERVE))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        cmd = [sys.executable, SERVE, path] + (["--mesh", mesh] if mesh else [])
        # stderr goes to a FILE, not a pipe: nobody drains a pipe (64KB of XLA
        # warnings would deadlock the child), and on a startup crash the file
        # holds the real traceback for the assertion message below
        self._errpath = errfile or (path + ".server-stderr")
        self._errf = open(self._errpath, "w")
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._errf, text=True, env=env)
        line = self.proc.stdout.readline()
        try:
            ready = json.loads(line)
        except json.JSONDecodeError:
            self._errf.flush()
            raise AssertionError(
                f"server died at startup; stderr tail:\n"
                f"{open(self._errpath).read()[-3000:]}") from None
        assert ready.get("ready"), ready

    def ask(self, **req):
        self.proc.stdin.write(json.dumps(req) + "\n")
        self.proc.stdin.flush()
        return json.loads(self.proc.stdout.readline())

    def close(self):
        try:
            self.ask(op="quit")
        except Exception:
            pass
        self.proc.wait(timeout=30)
        self._errf.close()


@pytest.mark.slow
def test_second_process_serves_checkpoint(tmp_path):
    sents = _corpus()
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=128,
                         num_iterations=1, window=2, negatives=3, negative_pool=8,
                         steps_per_dispatch=2, seed=9)
    trainer = Trainer(cfg, vocab)
    trainer.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    ck = str(tmp_path / "model")
    trainer.save_checkpoint(ck)

    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    local = Word2VecModel.load(ck)
    want = local.find_synonyms("w0", 5)

    srv = _Server(ck)
    try:
        info = srv.ask(op="info")
        assert info["num_words"] == vocab.size
        got = srv.ask(op="synonyms", word="w0", num=5)["synonyms"]
        assert [w for w, _ in got] == [w for w, _ in want]
        np.testing.assert_allclose([s for _, s in got], [s for _, s in want],
                                   rtol=1e-5)
        vec = srv.ask(op="vector", word="w1")["vector"]
        np.testing.assert_allclose(vec, local.transform("w1"), rtol=1e-6)

        # batched queries: one dispatch serves many words (PERF.md §6)
        batch = srv.ask(op="synonyms_batch", words=["w0", "w1"],
                        num=5)["synonyms"]
        assert [w for w, _ in batch[0]] == [w for w, _ in want]
        assert len(batch) == 2 and len(batch[1]) == 5

        # the trainer keeps going and writes a NEWER checkpoint at the same path;
        # the serving process picks it up with the reload op (mode-B lifecycle)
        trainer.fit(encode_sentences(_corpus(seed=5), vocab,
                                     cfg.max_sentence_length))
        trainer.save_checkpoint(ck)
        assert srv.ask(op="reload")["reloaded"]
        got2 = srv.ask(op="synonyms", word="w0", num=5)["synonyms"]
        want2 = Word2VecModel.load(ck).find_synonyms("w0", 5)
        assert [w for w, _ in got2] == [w for w, _ in want2]
    finally:
        srv.close()


@pytest.mark.slow
def test_concurrent_train_and_serve(tmp_path):
    """The reference's deployment story as ONE RUNNING SYSTEM (README.md:45-57):
    training runs and keeps writing checkpoints while a separate serving process
    reloads live and answers queries MID-TRAINING. Every reload must land on a
    consistent checkpoint (the swap/retry path in serve_checkpoint.py absorbs
    the atomic-swap window — no torn reads may surface as errors or as synonym
    rows outside the vocabulary)."""
    import threading

    sents = _corpus(n=600, seed=7)
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=128,
                         num_iterations=8, window=2, negatives=3,
                         negative_pool=8, steps_per_dispatch=2, seed=13)
    trainer = Trainer(cfg, vocab)
    encoded = encode_sentences(sents, vocab, cfg.max_sentence_length)
    ck = str(tmp_path / "model")
    trainer.save_checkpoint(ck)  # the server needs a first checkpoint to boot

    fit_err = []

    def fit():
        try:
            # checkpoint every 4 global steps: many atomic swaps race the
            # server's reloads below
            trainer.fit(encoded, checkpoint_path=ck, checkpoint_every_steps=4)
            trainer.save_checkpoint(ck)
        except Exception as e:  # noqa: BLE001 — re-raised via fit_err
            fit_err.append(e)

    srv = _Server(ck)
    t = threading.Thread(target=fit)
    try:
        t.start()
        cycles = 0
        words = {f"w{i}" for i in range(60)}
        while t.is_alive():
            r = srv.ask(op="reload")
            assert r.get("reloaded"), f"torn reload surfaced: {r}"
            assert r["num_words"] == vocab.size
            got = srv.ask(op="synonyms", word="w0", num=5)
            assert "error" not in got, got
            assert len(got["synonyms"]) == 5
            assert all(w in words and np.isfinite(s)
                       for w, s in got["synonyms"]), got
            info = srv.ask(op="info")
            assert "error" not in info, info
            cycles += 1
        t.join()
        assert not fit_err, fit_err
        # the overlap was real: many reload+query cycles ran during training
        assert cycles >= 5, f"only {cycles} cycles overlapped training"

        # after training: one more reload sees the FINAL checkpoint exactly
        assert srv.ask(op="reload")["reloaded"]
        from glint_word2vec_tpu.models.word2vec import Word2VecModel
        want = Word2VecModel.load(ck).find_synonyms("w0", 5)
        got = srv.ask(op="synonyms", word="w0", num=5)["synonyms"]
        assert [w for w, _ in got] == [w for w, _ in want]
    finally:
        srv.close()
        t.join(timeout=60)


@pytest.mark.slow
def test_serving_row_shards_onto_own_mesh(tmp_path):
    """Row-shards checkpoint served by a process that streams it onto its own 8-way
    mesh — no dense [V, D] host copy in the serving process."""
    sents = _corpus(seed=4)
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=128,
                         num_iterations=1, window=2, negatives=3, negative_pool=8,
                         steps_per_dispatch=2, seed=11, sharded_checkpoint=True)
    trainer = Trainer(cfg, vocab)
    trainer.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    ck = str(tmp_path / "model")
    trainer.save_checkpoint(ck)

    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    want = Word2VecModel.load(ck).find_synonyms("w0", 5)
    srv = _Server(ck, mesh="1x8")
    try:
        got = srv.ask(op="synonyms", word="w0", num=5)["synonyms"]
        assert [w for w, _ in got] == [w for w, _ in want]
    finally:
        srv.close()
