"""The telemetry JSONL schema: one versioned catalogue + the validator.

Every record the sink writes carries ``schema`` (this module's
:data:`SCHEMA_VERSION`), ``kind`` (one of :data:`KINDS`), and ``t`` (unix
seconds). The validator is the drift gate: tests and CI validate every
emitted file against the catalogue here, so a field rename/removal fails the
build instead of silently orphaning downstream consumers of old run logs.
Additive fields are fine (consumers must ignore unknown keys); renaming or
removing a required field — or changing a type — requires a version bump and
a catalogue entry, reviewed like any contract change.

Run as a CLI (the CI schema-validation step)::

    python -m glint_word2vec_tpu.obs.schema run.jsonl [more.jsonl ...]

Prints one JSON summary line on stdout; exit code 0 iff every record of
every file validates. Paths ending ``.blackbox.json`` are validated as
flight-recorder dumps (one JSON document whose ring entries reuse this
catalogue — obs/blackbox.py) instead of as JSONL.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1

# null is legal wherever a number is: the sink writes non-finite measured
# values (NaN loss in a diverging run) as null to keep every line strict
# RFC-8259 JSON (obs/sink.py _sanitize)
_NUM = (int, float, type(None))

# kind -> {field: allowed python types}. These are the REQUIRED fields; extra
# keys are always allowed (additive evolution).
KINDS: Dict[str, Dict[str, tuple]] = {
    "run_start": {
        "run_id": (str,),
        "vocab_size": (int,),
        "mesh": (list,),
        "config": (dict,),       # the stability-relevant knob subset
    },
    "heartbeat": {
        "step": (int,),
        "words": (int,),
        "alpha": _NUM,
        "loss": _NUM,
        "mean_f_pos": _NUM,
        "pairs_per_sec": _NUM,
        "host_wait_s": _NUM,     # host-side wait since the previous heartbeat
        "dispatch_s": _NUM,      # dispatch time since the previous heartbeat
        # optional fields (see KINDS_OPTIONAL): "norms", "phases",
        # "recoveries", "lr_scale"
    },
    "watchdog": {
        "step": (int,),
        "policy": (str,),        # "warn" | "recover" | "halt"
        "reason": (str,),
        "channels": (dict,),     # the probe channels the decision was made on
    },
    # one per norm_watch="recover" ladder action (ADDITIVE under the schema
    # evolution rule: a brand-new kind; no existing field moved). Emitted
    # BEFORE the rollback mutates any state, so even a crash mid-recovery
    # leaves the evidence in the run log — and the budget-exhaustion record
    # (action="halt") lands before the NormBlowupError raise, the same
    # record-before-raise contract as the watchdog-halt path.
    "recovery": {
        "step": (int,),          # global step the firing probe observed
        "action": (str,),        # "rollback" | "halt" (budget exhausted)
        "reason": (str,),        # the watchdog firing reason
        "snapshot_step": (int,), # restore point (-1 when action="halt")
        "recoveries_performed": (int,),  # AFTER this action
        "max_recoveries": (int,),
        "lr_scale": _NUM,        # effective lr multiplier AFTER this action
        "max_row_norm": _NUM,    # engaged clamp AFTER this action (0 = off)
        "channels": (dict,),
    },
    "run_end": {
        "run_id": (str,),
        "status": (str,),        # "ok" | "error" | "preempted" (emergency-
                                 # checkpoint exit — train/supervisor.py)
        "steps": (int,),
        "pairs_trained": _NUM,
        "host_wait_s_total": _NUM,
        "dispatch_s_total": _NUM,
        "watchdog_fires": (int,),
    },
    # --- serving-tier record kinds (serve/service.py; ADDITIVE under the
    # schema evolution rule, like "recovery": brand-new kinds, no existing
    # field moved — archived v1 training logs keep validating) ---
    "serve_start": {
        "checkpoint": (str,),    # path served ("<in-memory>" for model=)
        "vocab_size": (int,),
        "vector_size": (int,),
        # optional: "ann" (the built index's stats dict incl. recall)
    },
    "serve_reload": {
        "vocab_size": (int,),    # of the NEWLY installed model
        "reloads": (int,),       # total hot-reloads AFTER this one
        "load_seconds": _NUM,    # background load + index build wall time
    },
    "serve_stats": {
        "submitted": (int,),
        "refused": (int,),       # 429-style backpressure refusals
        "batches": (int,),
        "queue_depth": (int,),
        "reloads": (int,),
        # optional: "latency_ms", "occupancy_mean", "ann"
    },
    "serve_end": {
        "submitted": (int,),
        "refused": (int,),
        "reloads": (int,),
    },
    # --- serving-fleet record kinds (serve/fleet.py; ADDITIVE under the
    # schema evolution rule, like the serve_* tier: brand-new kinds, no
    # existing field moved — archived v1 logs keep validating) ---
    "fleet_start": {
        "replicas": (int,),      # fleet size behind the router
        "checkpoint": (str,),    # publish path ("<in-memory>" for adopted)
    },
    "fleet_breaker": {
        "replica": (str,),       # replica name (r0, r1, ...)
        "from_state": (str,),    # "closed" | "open" | "half-open"
        "to_state": (str,),
        "reason": (str,),        # bounded human diagnostic
    },
    "fleet_reload": {
        "publishes": (int,),     # rolling-reload rounds AFTER this one
        "min_serving": (int,),   # lowest serving count during the round
                                 # (the N-1 capacity-floor assertion)
        "replicas": (int,),
        "seconds": _NUM,         # whole-round wall time
    },
    "fleet_stats": {
        "queries": (int,),
        "failures": (int,),      # requests that exhausted the deadline
        "retries": (int,),       # failed attempts retried elsewhere
        "hedges": (int,),        # duplicate sends past the hedge delay
        "hedge_wins": (int,),    # hedges whose SECOND replica answered first
        "shed": (int,),          # bulk + single refusals (FleetOverloaded)
        "healthy": (int,),       # alive replicas with CLOSED breakers
        "degraded": (int,),      # serving a stale publish generation
    },
    "fleet_end": {
        "queries": (int,),
        "failures": (int,),
    },
    # --- fleet-observability record kinds (obs/trace.py, obs/slo.py;
    # ISSUE 13 — ADDITIVE under the schema evolution rule: brand-new kinds,
    # no existing field moved, archived v1 logs keep validating) ---
    # one measured region of one fleet query, in whichever PROCESS measured
    # it: the router's per-query root + per-attempt children, the replica
    # batcher's queue_wait/batch_service children, the service's ANN-probe
    # child. mono_ns is the process's monotonic clock; the collector maps it
    # to fleet wall time via the clock anchor its file's *_start record
    # carries (obs/collect.py).
    "trace_span": {
        "trace_id": (str,),      # one per client query (root of the tree)
        "span": (str,),          # this span's id
        "name": (str,),          # fleet_query | attempt | queue_wait |
                                 # batch_service | ann_probe | exact_scan
        "mono_ns": (int,),       # start, process-local monotonic clock
        "dur_ns": (int,),
        # optional: "parent" (absent on roots), "process", "replica",
        # "outcome" (ok|win|abandoned|failed|saturated|shed), "op"
    },
    # the publish-side correlation record: the trainer / ContinualRunner
    # emits one after a completed checkpoint save, keyed by the SAME
    # publish_sig string the watcher and fleet router compare — save ->
    # detect -> per-replica drain+reload becomes one collector-joinable
    # causal chain (obs/trace.emit_publish)
    "publish": {
        "publish_sig": (str,),   # mtime_ns-inode-size of metadata.json
        "checkpoint": (str,),
        "step": (int,),
    },
    # periodic SLO snapshot (obs/slo.py flatten_burn): availability over
    # the router's per-query samples + multi-window burn rates; null burn =
    # no budget math possible yet (no samples)
    "fleet_slo": {
        "objective": _NUM,       # availability objective (e.g. 0.999)
        "availability": _NUM,    # measured, tracker lifetime
        "samples": (int,),
        "burn_short": _NUM,      # short-window availability burn rate
        "burn_long": _NUM,
    },
    # --- continual-training record kinds (continual/loop.py; ADDITIVE under
    # the schema evolution rule, like the serve_* tier: brand-new kinds, no
    # existing field moved — archived v1 logs keep validating) ---
    "continual_extend": {
        "old_vocab_size": (int,),
        "new_vocab_size": (int,),
        "new_words": (int,),     # promoted past min_count this migration
    },
    "continual_increment": {
        "increment": (int,),     # 0 = the bootstrap base fit
        "segments": (int,),      # new tail segments trained this increment
        "vocab_size": (int,),    # AFTER any extension
        "new_words": (int,),
        "words": (int,),         # tail tokens trained
        "train_seconds": _NUM,
    },
    # --- training-supervisor record kinds (train/supervisor.py,
    # docs/robustness.md; ADDITIVE under the schema evolution rule) ---
    # the trainer's own last word under a preemption: emitted by
    # _preempt_exit right before run_end status="preempted", carrying
    # whether the emergency save made the deadline and how many steps
    # separate the carry from the last published checkpoint (the
    # progress-lost-since-last-save the supervisor and run_report report)
    "preempt": {
        "step": (int,),
        "saved": (bool,),        # emergency checkpoint published + verified
        "checkpoint": (str,),
        "deadline_s": _NUM,      # config.preempt_deadline_s
        "steps_since_save": (int,),  # 0 when saved — nothing was lost
    },
    # supervisor lifecycle: one sink per supervisor, distinct from the
    # child fits' sinks (each attempt writes its own run_* bracket)
    "supervisor_start": {
        "commands": (int,),      # gang size (1 = single-process fit)
        "max_restarts": (int,),
        "stall_s": _NUM,
    },
    "supervisor_exit": {         # one per child-process death, any cause
        "attempt": (int,),
        "rc": (int,),            # negative = killed by that signal
        "cls": (str,),           # ok|preempt|stall|crash|peer-death
        "step": (int,),          # last observed telemetry step
    },
    "supervisor_restart": {
        "attempt": (int,),       # the attempt ABOUT to start
        "backoff_s": _NUM,       # decorrelated-jitter sleep taken first
        "resume_step": (int,),   # step of the checkpoint resumed from
    },
    "supervisor_stall": {
        "attempt": (int,),
        "last_step": (int,),
        "stalled_s": _NUM,       # silence observed when the watchdog fired
    },
    "supervisor_quarantine": {
        "signature": (str,),     # the repeated (cls, step-bucket) signature
        "attempts": (int,),
        "ladder_stage": (int,),  # 1 = mitigations engaged, 2 = halted
    },
    "supervisor_end": {
        "status": (str,),        # ok | quarantined | gave-up
        "attempts": (int,),
        "final_step": (int,),
    },
}

_COMMON = {"schema": (int,), "kind": (str,), "t": _NUM}

# OPTIONAL fields: type-checked when present, never required — this is what
# "additive fields are free" means in practice. Round 13 added
# recoveries/lr_scale/phases here, NOT to the required table: every new
# writer emits them on every heartbeat (tests pin that), but archived v1
# logs (CI artifacts, old remote-run JSONLs) must keep validating — making
# a new field REQUIRED under an unchanged version number would retroactively
# invalidate every file the previous release wrote.
#
# Round 14 (ISSUE 13) adds the CLOCK ANCHORS here: every run_start /
# serve_start / fleet_start a new writer emits carries one simultaneous
# (wall_ns, mono_ns) clock reading (obs/trace.clock_anchor) so the
# collector can align cross-process monotonic timestamps — optional, not
# required, for exactly the archived-log reason above.
KINDS_OPTIONAL: Dict[str, Dict[str, tuple]] = {
    "run_start": {
        "wall_ns": (int,),       # time.time_ns() at the same instant as...
        "mono_ns": (int,),       # ...time.monotonic_ns() (the anchor pair)
    },
    "heartbeat": {
        "norms": (dict,),        # probe channels, when the probe ran
        "recoveries": (int,),    # recoveries performed so far this fit
        "lr_scale": _NUM,        # effective lr multiplier the heartbeat's
                                 # chunk actually DISPATCHED under
        "phases": (dict,),       # per-phase log2 duration histograms over
                                 # this heartbeat window (obs/phases.py)
    },
    "run_end": {
        "phases": (dict,),       # cumulative per-phase rollup
        "spans": (dict,),        # tracer span summary
    },
    "serve_start": {
        "ann": (dict,),          # IVF build stats (centroids, nprobe,
                                 # recall_at_10, build_seconds)
        "wall_ns": (int,),       # clock anchor (see run_start)
        "mono_ns": (int,),
        "process": (str,),       # fleet-timeline track label
        "publish_sig": (str,),   # the publish generation first served
    },
    "serve_reload": {
        "ann": (dict,),
        "vocab_grew_from": (int,),  # previous generation's V, present only
                                    # when the publish changed the vocab
                                    # size (continual growth)
        "publish_sig": (str,),   # the generation this reload installed —
                                 # joins the trainer's publish record
    },
    "serve_stats": {
        "latency_ms": (dict,),   # p50/p95/p99 over the recent-latency ring
        "occupancy_mean": _NUM,  # mean requests per dispatched batch
        "ann": (dict,),
    },
    "fleet_start": {
        "wall_ns": (int,),       # clock anchor (see run_start)
        "mono_ns": (int,),
        "process": (str,),
    },
    "fleet_reload": {
        "publish_sig": (str,),   # the generation the rolling round rolled to
    },
    "fleet_stats": {
        "latency_ms": (dict,),   # router-side end-to-end quantiles
        "slo": (dict,),          # obs/slo.py flatten_burn snapshot
    },
    "trace_span": {
        "parent": (str,),        # absent on root spans
        "process": (str,),
        "replica": (str,),       # attempt spans: which replica answered
        "outcome": (str,),       # ok|win|abandoned|failed|saturated|shed
        "op": (str,),
    },
    "publish": {
        "publisher": (str,),     # trainer | continual
    },
    "fleet_slo": {
        "latency_good_fraction": _NUM,
        "latency_burn_short": _NUM,
    },
}

# The flight-recorder dump (obs/blackbox.py, `<telemetry_path>.blackbox.json`)
# is ONE JSON document, not JSONL — its ring entries reuse the record kinds
# above, so the same catalogue validates both artifacts. Top-level required
# fields; `cause.kind` enumerates the terminal-record variants.
BLACKBOX_FIELDS: Dict[str, tuple] = {
    "run_id": (str,),
    "cause": (dict,),
    "heartbeats": (list,),
    "events": (list,),
    "dispatches": (list,),
}
_CAUSE_KINDS = ("exception", "signal", "none")
_DISPATCH_FIELDS: Dict[str, tuple] = {
    "t": _NUM, "step": (int,), "real": (int,),
    "dispatch_s": _NUM, "wait_s": _NUM,
}


def validate_record(rec: Any) -> List[str]:
    """Errors for one parsed record; empty list = valid."""
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    errs: List[str] = []
    for field, types in _COMMON.items():
        if field not in rec:
            errs.append(f"missing common field {field!r}")
        elif not isinstance(rec[field], types) or isinstance(rec[field], bool):
            errs.append(f"{field!r} has type {type(rec[field]).__name__}")
    if errs:
        return errs
    if rec["schema"] != SCHEMA_VERSION:
        return [f"schema version {rec['schema']} != {SCHEMA_VERSION} "
                f"(drift: bump the catalogue, not just the writer)"]
    kind = rec["kind"]
    if kind not in KINDS:
        return [f"unknown kind {kind!r}"]
    for field, types in KINDS[kind].items():
        if field not in rec:
            errs.append(f"{kind}: missing field {field!r}")
        elif not isinstance(rec[field], types) or (
                isinstance(rec[field], bool) and bool not in types):
            errs.append(f"{kind}.{field} has type {type(rec[field]).__name__}, "
                        f"expected {'/'.join(t.__name__ for t in types)}")
    for field, types in KINDS_OPTIONAL.get(kind, {}).items():
        if field in rec and rec[field] is not None and (
                not isinstance(rec[field], types)
                or (isinstance(rec[field], bool) and bool not in types)):
            errs.append(f"{kind}.{field} has type {type(rec[field]).__name__}, "
                        f"expected {'/'.join(t.__name__ for t in types)} "
                        f"(optional field: absent is fine, wrong type is not)")
    return errs


def validate_blackbox(doc: Any) -> List[str]:
    """Errors for one parsed blackbox dump document; empty list = valid.
    Ring entries are validated against the record catalogue above (they are
    the same records the sink wrote), dispatch records against their own
    field table, and the terminal ``cause`` against the variant enum."""
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, not an object"]
    errs: List[str] = []
    if doc.get("kind") != "blackbox":
        errs.append(f"kind is {doc.get('kind')!r}, expected 'blackbox'")
    if doc.get("schema") != SCHEMA_VERSION:
        errs.append(f"schema version {doc.get('schema')!r} != "
                    f"{SCHEMA_VERSION}")
    for field, types in BLACKBOX_FIELDS.items():
        if field not in doc:
            errs.append(f"missing field {field!r}")
        elif not isinstance(doc[field], types):
            errs.append(f"{field!r} has type {type(doc[field]).__name__}")
    if errs:
        return errs
    cause = doc["cause"]
    ck = cause.get("kind")
    if ck not in _CAUSE_KINDS:
        errs.append(f"cause.kind {ck!r} not in {_CAUSE_KINDS}")
    elif ck == "exception" and not (
            isinstance(cause.get("type"), str)
            and isinstance(cause.get("message"), str)):
        errs.append("exception cause needs string 'type' and 'message'")
    elif ck == "signal" and not isinstance(cause.get("signal"), str):
        errs.append("signal cause needs a string 'signal' name")
    for i, rec in enumerate(doc["heartbeats"]):
        for e in validate_record(rec):
            errs.append(f"heartbeats[{i}]: {e}")
        if isinstance(rec, dict) and rec.get("kind") != "heartbeat":
            errs.append(f"heartbeats[{i}]: kind {rec.get('kind')!r}")
    for i, rec in enumerate(doc["events"]):
        for e in validate_record(rec):
            errs.append(f"events[{i}]: {e}")
    for i, rec in enumerate(doc["dispatches"]):
        if not isinstance(rec, dict):
            errs.append(f"dispatches[{i}]: not an object")
            continue
        for field, types in _DISPATCH_FIELDS.items():
            if field not in rec:
                errs.append(f"dispatches[{i}]: missing {field!r}")
            elif not isinstance(rec[field], types) or isinstance(
                    rec[field], bool):
                errs.append(f"dispatches[{i}].{field} has type "
                            f"{type(rec[field]).__name__}")
    return errs


def validate_blackbox_file(path: str, max_errors: int = 20) -> Dict[str, Any]:
    """Validate one ``.blackbox.json`` dump; same summary shape as
    :func:`validate_file` so the CLI handles both artifact kinds."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return {"path": path, "records": 0, "kinds": {}, "ok": False,
                "errors": [f"{path}: unreadable ({e})"]}
    errors = [f"{path}: {e}" for e in validate_blackbox(doc)]
    kinds = {}
    if not errors:
        kinds = {"blackbox": 1,
                 "heartbeat": len(doc["heartbeats"]),
                 "event": len(doc["events"]),
                 "dispatch": len(doc["dispatches"])}
    return {"path": path, "records": 1 if not errors else 0, "kinds": kinds,
            "ok": not errors, "errors": errors[:max_errors]}


def validate_file(path: str, max_errors: int = 20,
                  tolerate_torn_tail: bool = False) -> Dict[str, Any]:
    """Validate every line of a telemetry JSONL file (rotated segments are
    just more files — pass each). Returns a summary dict with per-kind counts
    and the first ``max_errors`` error strings. A SIGKILLed process can leave
    a half-written FINAL line; ``tolerate_torn_tail=True`` reports that one
    as ``"torn_tail": true`` instead of an error — mid-file garbage still
    fails either way."""
    counts: Dict[str, int] = {}
    errors: List[str] = []
    n = 0
    tail_err: Optional[str] = None
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            n += 1
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                tail_err = f"{path}:{lineno}: not JSON ({e})"
                errors.append(tail_err)
                continue
            tail_err = None
            errs = validate_record(rec)
            if errs:
                errors.extend(f"{path}:{lineno}: {e}" for e in errs)
            else:
                counts[rec["kind"]] = counts.get(rec["kind"], 0) + 1
    torn = False
    if tolerate_torn_tail and tail_err is not None:
        errors.remove(tail_err)
        torn = True
    return {"path": path, "records": n, "kinds": counts, "torn_tail": torn,
            "ok": not errors, "errors": errors[:max_errors]}


def main(argv: List[str]) -> int:
    if not argv:
        print(json.dumps({"ok": False,
                          "errors": ["usage: python -m "
                                     "glint_word2vec_tpu.obs.schema "
                                     "FILE.jsonl [...]"]}))
        return 2
    results = [validate_blackbox_file(p) if p.endswith(".blackbox.json")
               else validate_file(p) for p in argv]
    ok = all(r["ok"] for r in results) and all(
        r["records"] > 0 for r in results)
    print(json.dumps({"ok": ok, "schema": SCHEMA_VERSION, "files": results}))
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(main(sys.argv[1:]))
