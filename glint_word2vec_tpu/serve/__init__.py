"""Production serving tier (docs/serving.md; ROADMAP item 1).

Four layers, composable alone or through :class:`EmbeddingService`:

- :mod:`.batcher` — bounded-queue, deadline-based micro-batcher that
  coalesces concurrent queries into one device dispatch, with 429-style
  fast refusal as backpressure.
- :mod:`.ann` — IVF (coarse k-means + inverted lists) approximate top-k
  over the trained matrix, built at load/publish time, ``nprobe``-tunable,
  with recall@k measured against the exact oracle at build — and gated:
  a quantized build below its recall floor refuses to publish.
- :mod:`.quant` — the quantized storage arms (int8 scalar, product
  quantization + ADC + exact re-rank) and the shard-native build that
  streams a row-shards checkpoint into codes without a dense [V, D]
  float32 copy (docs/serving.md §6).
- :mod:`.reload` — the swap-window-safe loader (single owner of the retry
  logic), the lease-counted swappable serving handle, and the
  checkpoint-publish watcher: zero-downtime hot-reload off the trainer's
  atomic ``.tmp-*``/``.old-*`` swap protocol.
- :mod:`.service` — the assembled service: batched exact/ANN queries,
  hot-reload, ``serve_*`` telemetry records and ``glint_serve_*``
  Prometheus gauges riding the existing obs layer.
- :mod:`.fleet` — the failure model ABOVE the service (ISSUE 12): N
  replicas behind a :class:`FleetRouter` with per-replica health probes
  and circuit breakers, deadline-budgeted retries, tail-latency hedging,
  graceful load shedding, and orchestrated rolling reload (capacity
  never below N-1 across a publish).
"""

from glint_word2vec_tpu.serve.ann import (
    IvfIndex,
    RecallFloorError,
    auto_centroids,
    auto_nprobe,
    build_ivf,
)
from glint_word2vec_tpu.serve.quant import (
    Int8Storage,
    PQStorage,
    ShardRowFetch,
    auto_pq_m,
    build_ivf_from_shards,
)
from glint_word2vec_tpu.serve.batcher import (
    BatchingScheduler,
    ServerOverloaded,
    ServiceClosed,
)
from glint_word2vec_tpu.serve.fleet import (
    CircuitBreaker,
    FleetOverloaded,
    FleetRouter,
    NoHealthyReplicas,
    ReplicaSet,
    fleet_knobs_from_checkpoint,
)
from glint_word2vec_tpu.serve.reload import (
    CheckpointWatcher,
    ServingHandle,
    decorrelated_jitter,
    load_with_retry,
)
from glint_word2vec_tpu.serve.service import EmbeddingService

__all__ = [
    "IvfIndex", "build_ivf", "auto_centroids", "auto_nprobe",
    "RecallFloorError", "build_ivf_from_shards", "auto_pq_m",
    "Int8Storage", "PQStorage", "ShardRowFetch",
    "BatchingScheduler", "ServerOverloaded", "ServiceClosed",
    "CheckpointWatcher", "ServingHandle", "load_with_retry",
    "decorrelated_jitter",
    "CircuitBreaker", "FleetOverloaded", "FleetRouter",
    "NoHealthyReplicas", "ReplicaSet", "fleet_knobs_from_checkpoint",
    "EmbeddingService",
]
