"""Persistence round-trip tests (G9/C13 analogs): words sidecar order, metadata,
mid-training state, load-time errors."""

import json
import os

import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.models.word2vec import Word2VecModel
from glint_word2vec_tpu.train.checkpoint import TrainState, load_model, save_model


@pytest.fixture
def saved(tmp_path):
    words = ["w0", "w1", "w2"]
    counts = np.array([30, 20, 10])
    syn0 = np.arange(12, dtype=np.float32).reshape(3, 4)
    syn1 = -syn0
    cfg = Word2VecConfig(vector_size=4, seed=7)
    path = str(tmp_path / "model")
    save_model(path, words, counts, syn0, syn1, cfg,
               TrainState(iteration=2, words_processed=123, finished=False))
    return path, words, counts, syn0, syn1, cfg


def test_words_sidecar_format(saved):
    """One word per line, line order == row order — exact parity with the reference's
    sidecar (mllib:495-496,714-715)."""
    path, words, *_ = saved
    with open(os.path.join(path, "words")) as f:
        assert f.read() == "w0\nw1\nw2\n"


def test_roundtrip(saved):
    path, words, counts, syn0, syn1, cfg = saved
    data = load_model(path)
    assert data["words"] == words
    np.testing.assert_array_equal(data["counts"], counts)
    np.testing.assert_array_equal(data["syn0"], syn0)
    np.testing.assert_array_equal(data["syn1"], syn1)
    assert data["config"].vector_size == 4 and data["config"].seed == 7
    st = data["train_state"]
    assert (st.iteration, st.words_processed, st.finished) == (2, 123, False)


def test_model_save_load_roundtrip(tmp_path):
    vocab = Vocabulary.from_words_and_counts(["a", "b"], [5, 3])
    syn0 = np.random.default_rng(0).normal(size=(2, 4)).astype(np.float32)
    m = Word2VecModel(vocab, syn0, config=Word2VecConfig(vector_size=4))
    path = str(tmp_path / "m")
    m.save(path)
    m2 = Word2VecModel.load(path)
    np.testing.assert_allclose(m2.transform("a"), syn0[0], rtol=1e-6)
    assert m2.vocab.words == ["a", "b"]
    assert m2.syn1 is None  # not saved when absent
    assert m2.train_state.finished


def test_load_missing_metadata(tmp_path):
    with pytest.raises(FileNotFoundError, match="metadata.json"):
        load_model(str(tmp_path / "nope"))


def test_load_bad_version(saved):
    path = saved[0]
    meta_file = os.path.join(path, "metadata.json")
    with open(meta_file) as f:
        meta = json.load(f)
    meta["format_version"] = 99
    with open(meta_file, "w") as f:
        json.dump(meta, f)
    with pytest.raises(ValueError, match="format_version"):
        load_model(path)


def test_load_words_matrix_mismatch(saved):
    path = saved[0]
    with open(os.path.join(path, "words"), "a") as f:
        f.write("extra\n")
    with pytest.raises(ValueError, match="sidecar"):
        load_model(path)
