"""Realistic-scale training quality evaluation (round-3, VERDICT item 3).

The BASELINE real corpora (text8, enwiki) are NOT reachable in this environment: the
build sandbox has zero network egress and no copy exists on disk (verified by a
filesystem-wide search). This harness substitutes the closest honest thing: a corpus at
**text8 scale** (17M words, ~70k effective vocabulary) drawn from a fully-specified
generative topic model, so embedding quality is *quantitatively* measurable against the
generator's ground truth instead of eyeballed. When a real text8 is available, drop it
at --corpus and the same pipeline trains on it unchanged (quality metrics then need an
external word-sim dataset; the throughput numbers stay comparable).

Generative model (deterministic given --seed):
    - V_raw word types with Zipf marginals p(r) ∝ 1/(r+10)^1.05 (text8-like head/tail)
    - the S most frequent types are topic-neutral "stopwords"
    - every other type r belongs to topic (r mod T); names encode the topic
      ("t017_w000421") so ground truth travels with the corpus file itself
    - each sentence draws one topic z; every word is, with prob λ, drawn from the
      renormalized marginals of topic z's own words, else from the global marginals
      (stopword/noise mass)

A good embedding must therefore cluster same-topic words. Metrics (ground truth = the
name prefix; random-vector baseline ≈ 1/T):
    - purity@10: fraction of a word's 10 cosine-nearest non-stopword neighbors sharing
      its topic, averaged over 2,000 mid-frequency probe words
    - margin: mean within-topic cosine minus mean cross-topic cosine over the probes

The run exercises the production ingestion path end-to-end: token FILE →
TokenFileCorpus → streaming vocab pass → encode_corpus (memory-mapped shards) →
Trainer → model ops. Prints one JSON line on stdout; progress goes to stderr.

Usage:
    python tools/eval_quality.py [--words 17000000] [--out /tmp/eval_corpus]
                                 [--corpus existing.txt] [--dim 100] [--iters 3]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))

T_TOPICS = 128
STOPWORDS = 200
LAMBDA = 0.72
SENT_LEN = 35
V_RAW = 90_000   # raw types; min_count=5 trims the tail to ~text8's ~70k

# Relational structure — the synthetic analog of the toy corpus's country/capital
# pairs (it spec:22-37): entity pair members co-occur with a topic's words; a-words
# additionally co-occur with a role-A word set, b-words with role-B, so the
# embedding must place b_i - a_i ≈ roleB - roleA — exactly what the reference's
# analogy gate (wien - österreich + deutschland ≈ berlin, it spec:327-352)
# measures, quantitatively at 90k-vocab scale with accuracy@1.
#
# v2 (round-5, VERDICT item 4): the v1 gate saturated (every 90k headline run
# scored acc@1 = 1.000 — it could no longer rank configs). v2 hardens it with
# THREE relation families, each with its OWN role-word sets (family offsets
# differ, so cross-family confusion is possible), a 15x lower total relation-
# sentence rate (0.06 -> 0.004), a 1:many family, and a rare family:
#   freq — 40 one-to-one pairs, 60% of relation sentences (the v1 regime, thinner)
#   many — 32 a-entities x 2 b-entities each (1:many), 32%
#   rare — 24 one-to-one pairs, 8% (~0.0013% of ALL sentences per pair —
#          ~23 sentences per pair / ~11 per side at 60M words: an
#          undertraining probe)
GEN_VERSION = 2
# Tuned DOWN until the 60M-word/d300 headline config lands off the ceiling
# (the first v2 candidate at 2.5%/0.18/0.30 still scored 1.0 everywhere):
REL_SENT_FRAC = 0.004  # fraction of sentences that are relation sentences (v1: 0.06)
FAMILIES = (
    {"key": "freq", "na": 40, "nb_per_a": 1, "weight": 0.60},
    {"key": "many", "na": 32, "nb_per_a": 2, "weight": 0.32},
    {"key": "rare", "na": 24, "nb_per_a": 1, "weight": 0.08},
)
ROLE_WORDS = 60        # per role set (each family has its own A and B sets)
REL_LAMBDA_ENTITY = 0.06  # slots holding the entity word itself
REL_LAMBDA_ROLE = 0.10    # slots drawn from the role word set; rest: topic/noise

# v1 layout (kept so --rescore still scores round-4 models)
N_ENTITIES = 96


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def topic_of(rank: np.ndarray) -> np.ndarray:
    """Ground-truth topic of a word rank; stopwords get -1."""
    return np.where(rank < STOPWORDS, -1, rank % T_TOPICS)


def word_names(v: int) -> np.ndarray:
    ranks = np.arange(v)
    topics = topic_of(ranks)
    return np.asarray([
        f"s_w{r:06d}" if t < 0 else f"t{t:03d}_w{r:06d}"
        for r, t in zip(ranks, topics)])


def relation_names():
    """v1 entity/role word types (kept for --rescore of round-4 models)."""
    ea = [f"ea_{i:03d}" for i in range(N_ENTITIES)]
    eb = [f"eb_{i:03d}" for i in range(N_ENTITIES)]
    ra = [f"ra_w{i:03d}" for i in range(ROLE_WORDS)]
    rb = [f"rb_w{i:03d}" for i in range(ROLE_WORDS)]
    return ea, eb, ra, rb


def family_names():
    """v2 per-family entity/role word types, appended after the V_RAW topic
    types in family order: a-entities, b-entities (a-major: b's of a-entity i
    are indices i*nb_per_a .. i*nb_per_a+nb_per_a-1), role-A, role-B."""
    fams = []
    for f_idx, fam in enumerate(FAMILIES):
        nb = fam["na"] * fam["nb_per_a"]
        fams.append({
            "key": fam["key"],
            "a": [f"f{f_idx}a_{i:03d}" for i in range(fam["na"])],
            "b": [f"f{f_idx}b_{i:03d}" for i in range(nb)],
            "ra": [f"r{f_idx}a_w{i:03d}" for i in range(ROLE_WORDS)],
            "rb": [f"r{f_idx}b_w{i:03d}" for i in range(ROLE_WORDS)],
            "nb_per_a": fam["nb_per_a"],
        })
    return fams


def generate_corpus(path: str, n_words: int, seed: int, v_raw: int = V_RAW) -> None:
    """Write the topic-model corpus as a token file, one sentence per line
    (v2 relation structure — see the constants block).

    A REL_SENT_FRAC fraction of sentences are relation sentences: one family
    drawn by weight, one of its entity words (a_i, or one of a_i's b's) + that
    family's role-set draws + the entity's topic words + noise."""
    rng = np.random.default_rng(seed)
    p = 1.0 / (np.arange(v_raw) + 10.0) ** 1.05
    p /= p.sum()
    names = word_names(v_raw)
    fams = family_names()
    all_names = np.concatenate(
        [names] + [np.asarray(f[k]) for f in fams for k in ("a", "b", "ra", "rb")])
    topics = topic_of(np.arange(v_raw))
    topic_words = [np.where(topics == z)[0] for z in range(T_TOPICS)]
    topic_probs = [p[w] / p[w].sum() for w in topic_words]
    # id layout mirrors all_names: per family, a / b / role-A / role-B blocks
    base = v_raw
    fam_ids = []
    fam_off = []
    for f_idx, f in enumerate(fams):
        ids = {"a": base + np.arange(len(f["a"]))}
        base += len(f["a"])
        ids["b"] = base + np.arange(len(f["b"]))
        base += len(f["b"])
        ids["ra"] = base + np.arange(ROLE_WORDS)
        base += ROLE_WORDS
        ids["rb"] = base + np.arange(ROLE_WORDS)
        base += ROLE_WORDS
        fam_ids.append(ids)
        # family topic offset: a-entity i of family f sits in topic
        # (17*f + i) mod T — distinct families' entities spread over topics
        fam_off.append(17 * f_idx)
    weights = np.asarray([f["weight"] for f in FAMILIES], np.float64)
    weights /= weights.sum()

    n_sents = n_words // SENT_LEN
    t0 = time.perf_counter()
    with open(path, "w", encoding="utf-8") as f:
        block = 20_000
        for start in range(0, n_sents, block):
            nb = min(block, n_sents - start)
            z = rng.integers(0, T_TOPICS, nb)
            words = np.empty((nb, SENT_LEN), np.int32)
            # global (stopword/noise) draws for every slot, then overwrite the
            # topic-bound slots per topic group
            words[:] = rng.choice(v_raw, size=(nb, SENT_LEN), p=p)
            from_topic = rng.random((nb, SENT_LEN)) < LAMBDA
            # relation sentences: family by weight, entity within family,
            # side a/b 50:50 (b: uniform over the a-entity's b's); the topic is
            # forced to the entity's own topic
            is_rel = rng.random(nb) < REL_SENT_FRAC
            fam_draw = rng.choice(len(FAMILIES), size=nb, p=weights)
            ent_word = np.zeros(nb, np.int32)
            for f_idx, (fam, ids) in enumerate(zip(FAMILIES, fam_ids)):
                rows = np.where(is_rel & (fam_draw == f_idx))[0]
                if not rows.size:
                    continue
                ai = rng.integers(0, fam["na"], rows.size)
                side_b = rng.random(rows.size) < 0.5
                bk = ai * fam["nb_per_a"] + rng.integers(
                    0, fam["nb_per_a"], rows.size)
                ent_word[rows] = np.where(side_b, ids["b"][bk], ids["a"][ai])
                z[rows] = (fam_off[f_idx] + ai) % T_TOPICS
                # role draws happen below against the row's family sets
            for zz in np.unique(z):
                rows = np.where(z == zz)[0]
                m = from_topic[rows]
                words[np.repeat(rows, m.sum(1)),
                      np.concatenate([np.where(r)[0] for r in m])] = rng.choice(
                    topic_words[zz], size=int(m.sum()), p=topic_probs[zz])
            # overwrite entity/role slots of relation sentences
            rel_rows = np.where(is_rel)[0]
            if rel_rows.size:
                u = rng.random((rel_rows.size, SENT_LEN))
                ent_slot = u < REL_LAMBDA_ENTITY
                role_slot = (u >= REL_LAMBDA_ENTITY) & (
                    u < REL_LAMBDA_ENTITY + REL_LAMBDA_ROLE)
                # the entity's side decides the role set: a-side rows draw from
                # the family's role-A set, b-side from role-B
                rw = np.empty((rel_rows.size, SENT_LEN), np.int32)
                for f_idx, ids in enumerate(fam_ids):
                    sub = np.where(fam_draw[rel_rows] == f_idx)[0]
                    if not sub.size:
                        continue
                    on_b = np.isin(ent_word[rel_rows[sub]], ids["b"])
                    draw = rng.integers(0, ROLE_WORDS, (sub.size, SENT_LEN))
                    rw[sub] = np.where(on_b[:, None], ids["rb"][draw],
                                       ids["ra"][draw])
                sub = words[rel_rows]
                sub = np.where(ent_slot, ent_word[rel_rows, None], sub)
                sub = np.where(role_slot, rw, sub)
                words[rel_rows] = sub
            lines = [" ".join(all_names[row]) for row in words]
            f.write("\n".join(lines) + "\n")
    n_pairs = sum(f["na"] * f["nb_per_a"] for f in FAMILIES)
    log(f"corpus v{GEN_VERSION}: {n_sents:,} sentences / "
        f"{n_sents * SENT_LEN:,} words ({REL_SENT_FRAC:.1%} relation sentences, "
        f"{n_pairs} entity pairs over {len(FAMILIES)} families) "
        f"written in {time.perf_counter() - t0:.1f}s -> {path}")


def evaluate(words, emb: np.ndarray, index=None) -> dict:
    """Topic purity@10 + cosine margin over 2,000 mid-frequency probe words, with a
    random-embedding baseline for scale. All big reductions (similarities, top-k,
    masked means) run ON DEVICE and only tiny results come back — fetching a
    [probes, content] matrix over the remote tunnel takes tens of minutes at 1M
    vocab (measured the hard way)."""
    import jax
    import jax.numpy as jnp

    # entity/role types (ea_/eb_/ra_/rb_) carry no topic; exclude from purity
    is_topic_word = np.asarray(
        [w.startswith(("t", "s_")) and "_w" in w for w in words])
    ranks_in_vocab = np.asarray(
        [int(w.split("_w")[1]) if ok else -1
         for w, ok in zip(words, is_topic_word)])
    topics = np.where(is_topic_word, topic_of(ranks_in_vocab), -1)
    content = np.where(topics >= 0)[0]
    if content.size > 250_000:
        # a fixed 250k-content sample keeps neighbor statistics intact
        content = np.sort(np.random.default_rng(3).choice(
            content, size=250_000, replace=False))
    # mid-frequency probes: skip the hottest 2k (near-uniform co-occurrence) and the
    # rarest tail (too few updates); small --vocab runs fall back to all content
    lo = min(2000, content.size // 4)
    hi = max(30000, lo + 1)
    probe_pool = content[(content >= lo) & (content < hi)]
    if probe_pool.size == 0:
        probe_pool = content
    rng = np.random.default_rng(0)
    probes = rng.choice(probe_pool, size=min(2000, probe_pool.size), replace=False)
    # probe position within content (probes are drawn from content)
    self_pos = np.searchsorted(content, probes)
    topics_probes = jnp.asarray(topics[probes])
    topics_content = jnp.asarray(topics[content])

    @jax.jit
    def device_purity(q, base, self_idx, t_probes, t_content):
        qn = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        bn = base / jnp.maximum(jnp.linalg.norm(base, axis=1, keepdims=True), 1e-12)
        sims = qn @ bn.T                                    # [P, C] — stays on device
        rows = jnp.arange(sims.shape[0])
        sims = sims.at[rows, self_idx].set(-jnp.inf)
        _, top = jax.lax.top_k(sims, 10)                    # [P, 10]
        neigh = t_content[top]
        pur = (neigh == t_probes[:, None]).mean()
        sub = sims[:, :4000]
        same = t_content[None, :4000] == t_probes[:, None]
        finite = jnp.isfinite(sub)
        sub0 = jnp.where(finite, sub, 0.0)
        within = (sub0 * (same & finite)).sum() / jnp.maximum(
            (same & finite).sum(), 1)
        cross = (sub0 * (~same & finite)).sum() / jnp.maximum(
            (~same & finite).sum(), 1)
        return pur, within - cross

    def purity(e):
        pur, margin = device_purity(
            jnp.asarray(e[probes]), jnp.asarray(e[content]),
            jnp.asarray(self_pos), topics_probes, topics_content)
        return float(pur), float(margin)

    if np.isnan(emb).any():
        return {"diverged": True,
                "nan_rows": int(np.isnan(emb).any(axis=1).sum())}
    # finite-overflow telemetry: a slow-burn instability can wreck the geometry
    # without ever reaching NaN (bf16 saturates at ~3.4e38 but quality collapses
    # orders of magnitude earlier); record the scale so collapsed-purity rows
    # are interpretable as blowup vs undertraining
    row_max = np.abs(emb).max(axis=1)
    rows_inf = int(np.isinf(row_max).sum())
    if rows_inf:
        # bf16 saturation reached ±inf: mask the blown entries out of the
        # scoring (an inf row would NaN-poison every cosine it touches, the
        # same silent distortion the NaN path guards against) and clamp the
        # telemetry to a finite float so the EVAL_RUNS.jsonl row stays strict
        # JSON ('Infinity' is a json.dumps extension strict parsers reject)
        emb = np.where(np.isfinite(emb), emb, 0.0).astype(emb.dtype, copy=False)
    abs_max = float(np.minimum(row_max.max(), np.finfo(np.float32).max))
    blown = int((row_max > 100.0).sum())
    # norm channels (ISSUE 6 / ROADMAP item 2): the same row-L2-norm signals
    # the trainer's fused health probe reports (obs/probe.py), computed on the
    # final embedding so EVAL_RUNS rows let the large-vocab ladder correlate
    # quality collapse with the norm trajectory the watchdog thresholds watch.
    # Computed AFTER the inf-masking above so the row stays strict JSON; the
    # rows_inf field already counts what the mask removed. Threshold 100.0 ==
    # the config default norm_watch_threshold (provenance in the config doc).
    row_norm = np.linalg.norm(emb.astype(np.float64), axis=1)
    fmax = float(np.finfo(np.float32).max)
    norm_channels = {
        "row_norm_max": round(float(min(row_norm.max(), fmax)), 3),
        "row_norm_p99": round(float(min(np.percentile(row_norm, 99), fmax)), 3),
        "row_norm_mean": round(float(min(row_norm.mean(), fmax)), 4),
        "rows_norm_over_100": int((row_norm > 100.0).sum()),
    }
    pur, margin = purity(emb)
    rnd = np.random.default_rng(1).standard_normal(
        emb.shape, dtype=np.float32)
    pur0, margin0 = purity(rnd)
    # serving-index health (ISSUE 10 / docs/serving.md): build the IVF
    # index exactly as checkpoint-publish does (serve/ann.py) and record
    # its oracle-checked recall@10, so the quality ladder catches index
    # degradation — a geometry that breaks IVF's clustering assumption
    # (e.g. post-norm-blowup spread) shows up here before a deployment
    # serves it. Best-effort: a failed build must not kill the quality row.
    ann_channels = {}
    try:
        from glint_word2vec_tpu.serve.ann import build_ivf
        t_ann = time.perf_counter()
        ivf = build_ivf(emb, seed=0, recall_queries=256, recall_k=10)
        ann_channels = {
            "ann_recall_at_10": ivf.stats.get("recall_at_10"),
            "ann_centroids": ivf.stats["centroids"],
            "ann_nprobe": ivf.stats["nprobe"],
            "ann_build_s": round(time.perf_counter() - t_ann, 2),
            "ann_index_bytes": ivf.stats.get("index_bytes"),
        }
        # quantized-arm recall channels (ISSUE 18): the same oracle
        # discipline for the int8/PQ builds a deployment would actually
        # serve — a geometry that quantizes badly (e.g. heavy-tailed rows
        # blowing the per-row int8 scale) shows up here before a
        # RecallFloorError does at publish. Floors off: this is the
        # MEASUREMENT channel; refusal is the serving tier's job.
        for quant in ("int8", "pq"):
            try:
                qix = build_ivf(emb, seed=0, recall_queries=256,
                                recall_k=10, quant=quant, recall_floor=0.0)
                ann_channels[f"ann_{quant}_recall_at_10"] = (
                    qix.stats.get("recall_at_10"))
                ann_channels[f"ann_{quant}_index_bytes"] = (
                    qix.stats.get("index_bytes"))
            except Exception as e:  # noqa: BLE001 — additive channel
                log(f"ann {quant} channel skipped: "
                    f"{type(e).__name__}: {e}")
    except Exception as e:  # noqa: BLE001 — index health is additive
        log(f"ann recall channel skipped: {type(e).__name__}: {e}")
    out = {
        "purity_at_10": round(pur, 4),
        "emb_abs_max": round(abs_max, 3),
        "rows_inf": rows_inf,
        "rows_abs_over_100": blown,
        **norm_channels,
        **ann_channels,
        "purity_at_10_random_baseline": round(pur0, 4),
        "cosine_margin": round(margin, 4),
        "cosine_margin_random_baseline": round(margin0, 4),
        "probes": int(probes.size),
        "topics": T_TOPICS,
    }
    if index is None:
        index = {w: i for i, w in enumerate(words)}
    out.update(evaluate_analogies(index, emb))
    return out


def evaluate_analogies(index, emb: np.ndarray) -> dict:
    """The reference's analogy gate (wien − österreich + deutschland ≈ berlin,
    it spec:327-352) run quantitatively over the generator's entity pairs:
    for a-entities (i, j), query v = b_i − a_i + a_j and check that the
    cosine-nearest word over the FULL vocabulary (query words excluded, like the
    reference's findSynonyms excludes the query) is one of a_j's b-entities.
    v2: scored PER FAMILY (freq / many / rare) plus the overall mean, so the
    gate ranks configs instead of saturating. Device-side: at 1M vocab the
    [queries, V] similarity matrix must not cross to the host."""
    import jax
    import jax.numpy as jnp

    fams = family_names()
    if fams[0]["a"][0] not in index and relation_names()[0][0] in index:
        return _evaluate_analogies_v1(index, emb)

    en_all = jnp.asarray(emb)

    @jax.jit
    def device_analogy(e, a_i, b_ik, a_j, b_j_set):
        # b_j_set: [n_q, nb] — ALL correct answers for a_j (1:many families)
        en = e / jnp.maximum(jnp.linalg.norm(e, axis=1, keepdims=True), 1e-12)
        v = en[b_ik] - en[a_i] + en[a_j]
        v = v / jnp.maximum(jnp.linalg.norm(v, axis=1, keepdims=True), 1e-12)
        sims = v @ en.T                       # [n_q, V] — stays on device
        rows = jnp.arange(sims.shape[0])
        cos_correct = jnp.take_along_axis(sims, b_j_set, axis=1).max(axis=1)
        sims = sims.at[rows, a_i].set(-jnp.inf)
        sims = sims.at[rows, b_ik].set(-jnp.inf)
        sims = sims.at[rows, a_j].set(-jnp.inf)
        top1 = sims.argmax(axis=1)
        hit = (top1[:, None] == b_j_set).any(axis=1)
        return hit.mean(), cos_correct.mean()

    rng = np.random.default_rng(7)
    out = {}
    accs, total_pairs, total_q = [], 0, 0
    for fam in fams:
        ia = np.asarray([index.get(w, -1) for w in fam["a"]])
        ib = np.asarray([index.get(w, -1) for w in fam["b"]])
        nb = fam["nb_per_a"]
        ok_a = (ia >= 0) & (ib.reshape(-1, nb) >= 0).all(axis=1)
        a_ids = ia[ok_a]
        b_sets = ib.reshape(-1, nb)[ok_a]     # [na_ok, nb]
        n = a_ids.size
        total_pairs += int((ib >= 0).sum())
        if n < 4:
            out[f"analogy_{fam['key']}_pairs"] = int(n)
            continue
        n_q = min(256, n * (n - 1) * nb)
        qi = rng.integers(0, n, n_q)
        qk = rng.integers(0, nb, n_q)
        qj = rng.integers(0, n - 1, n_q)
        qj = np.where(qj >= qi, qj + 1, qj)   # j != i
        acc, cos_mean = device_analogy(
            en_all, jnp.asarray(a_ids[qi]), jnp.asarray(b_sets[qi, qk]),
            jnp.asarray(a_ids[qj]), jnp.asarray(b_sets[qj]))
        out[f"analogy_{fam['key']}_accuracy_at_1"] = round(float(acc), 4)
        out[f"analogy_{fam['key']}_mean_cosine"] = round(float(cos_mean), 4)
        accs.append(float(acc))
        total_q += n_q
    if accs:
        out["analogy_accuracy_at_1"] = round(float(np.mean(accs)), 4)
    out["analogy_pairs_in_vocab"] = total_pairs
    out["analogy_queries"] = total_q
    out["gen_version"] = GEN_VERSION
    return out


def _evaluate_analogies_v1(index, emb: np.ndarray) -> dict:
    """v1 single-relation scoring — kept so --rescore works on round-4 models."""
    import jax
    import jax.numpy as jnp

    ea, eb, _, _ = relation_names()
    ia = np.asarray([index.get(w, -1) for w in ea])
    ib = np.asarray([index.get(w, -1) for w in eb])
    ok = (ia >= 0) & (ib >= 0)
    ia, ib = ia[ok], ib[ok]
    n = ia.size
    if n < 4:
        return {"analogy_pairs_in_vocab": int(n)}
    rng = np.random.default_rng(7)
    n_q = min(512, n * (n - 1))
    qi = rng.integers(0, n, n_q)
    qj = rng.integers(0, n - 1, n_q)
    qj = np.where(qj >= qi, qj + 1, qj)       # j != i

    @jax.jit
    def device_analogy(e, a_i, b_i, a_j, b_j):
        en = e / jnp.maximum(jnp.linalg.norm(e, axis=1, keepdims=True), 1e-12)
        v = en[b_i] - en[a_i] + en[a_j]
        v = v / jnp.maximum(jnp.linalg.norm(v, axis=1, keepdims=True), 1e-12)
        sims = v @ en.T                       # [n_q, V] — stays on device
        rows = jnp.arange(sims.shape[0])
        cos_correct = sims[rows, b_j]
        sims = sims.at[rows, a_i].set(-jnp.inf)
        sims = sims.at[rows, b_i].set(-jnp.inf)
        sims = sims.at[rows, a_j].set(-jnp.inf)
        top1 = sims.argmax(axis=1)
        return (top1 == b_j).mean(), cos_correct.mean()

    acc, cos_mean = device_analogy(
        jnp.asarray(emb), jnp.asarray(ia[qi]), jnp.asarray(ib[qi]),
        jnp.asarray(ia[qj]), jnp.asarray(ib[qj]))
    return {
        "analogy_pairs_in_vocab": int(n),
        "analogy_queries": int(n_q),
        "analogy_accuracy_at_1": round(float(acc), 4),
        "analogy_mean_cosine_to_answer": round(float(cos_mean), 4),
        "gen_version": 1,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--words", type=int, default=17_000_000)
    ap.add_argument("--out", default="/tmp/eval_corpus")
    ap.add_argument("--corpus", default=None,
                    help="existing token file (e.g. a real text8); skips generation "
                         "AND the ground-truth quality metrics")
    ap.add_argument("--dim", type=int, default=100)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--param-dtype", default="float32")
    ap.add_argument("--logits-dtype", default=None,
                    help="negative-logit chain dtype; defaults to float32 "
                         "(bfloat16 = the PERF.md fast path)")
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--vocab", type=int, default=V_RAW,
                    help="raw word types in the generator (before min_count)")
    ap.add_argument("--min-count", type=int, default=5)
    ap.add_argument("--subsample", type=float, default=1e-4)
    ap.add_argument("--lr", type=float, default=None,
                    help="learning rate; default 0.025 (the 1.6M-vocab "
                         "120M-word ladder rung measured a finite blowup at "
                         "that default — lower lr is the mitigation probe). "
                         "--rescore rows record this only when given "
                         "explicitly (the saved model's lr is unknowable)")
    ap.add_argument("--device-pairgen", action="store_true",
                    help="use the on-device pair generator feed")
    ap.add_argument("--cbow", action="store_true",
                    help="train the CBOW variant (BASELINE config 5)")
    ap.add_argument("--rescore", action="store_true",
                    help="skip training: score the syn0.npy + vocab_words.txt "
                         "already saved under --out (e.g. after an interrupted "
                         "metrics pass)")
    ap.add_argument("--pool", type=int, default=512,
                    help="shared negative pool. Scale it with the batch: every pool "
                         "row absorbs all pairs' negative gradients x negatives/pool, "
                         "and the pool + duplicate-context channels compound on "
                         "frequent rows over long runs (measured: B=64k/P=64 NaNs at "
                         "17M words; B=64k/P=256 is stable at 17M but NaNs at 60M; "
                         "P>=512 holds at 60M; see EVAL.md)")
    # --- in-step stabilizers + recovery (ISSUE 7 / ROADMAP 2): the ladder's
    # judge. Rows carry both the REQUESTED knobs and the ENGAGED end state
    # (recoveries_performed, lr_scale_final, engaged_max_row_norm) so the
    # collapse-rung ladder compares mitigation variants on purity/analogy
    # instead of vibes ---
    ap.add_argument("--max-row-norm", type=float, default=0.0,
                    help="per-touched-row L2 clamp on the update path (0=off)")
    ap.add_argument("--update-clip", type=float, default=0.0,
                    help="per-row L2 ceiling on each pair's update rows (0=off)")
    ap.add_argument("--row-l2", type=float, default=0.0,
                    help="touched-row weight decay (0=off)")
    ap.add_argument("--norm-watch", default="off",
                    choices=["off", "warn", "recover", "halt"],
                    help="finite-blowup watchdog policy for the trained run "
                         "('recover' = the full auto-recovery ladder)")
    # --- continual forgetting gate (ISSUE 11 / docs/continual.md): train a
    # base model, run ONE continual increment over a drifted corpus tail
    # (new word types + shifted frequencies), and score the ORIGINAL
    # vocabulary's purity/analogy before and after — catastrophic
    # forgetting as a gated number (two EVAL_RUNS rows), not a vibe ---
    ap.add_argument("--continual-ab", action="store_true",
                    help="base fit -> one continual increment on a drifted "
                         "tail -> score the ORIGINAL vocab pre/post; emits "
                         "one EVAL_RUNS row per arm (continual_ab_arm="
                         "pre/post)")
    ap.add_argument("--continual-tail-words", type=int, default=None,
                    help="drift-tail size in words (default: --words // 4)")
    ap.add_argument("--continual-new-types", type=int, default=2000,
                    help="extra raw word types in the tail generator "
                         "(ranks past --vocab become NEW words)")
    ap.add_argument("--continual-lr-rewarm", type=float, default=1.0,
                    help="continual_lr_rewarm for the increment")
    ap.add_argument("--continual-iterations", type=int, default=1,
                    help="continual_iterations for the increment")
    # --- hot-row parity gate (ISSUE 14 / PERF.md §11): the cross-step
    # hot-row accumulation changes FP rounding order (f32 slab accumulation
    # + one flush per chunk instead of per-step param-dtype rounding), so it
    # ships default-off behind THIS measured A/B: two arms on the identical
    # corpus/seed, the original per-step scatters vs hot_rows, scored on the
    # same ladder metrics. Documented tolerance: the hot arm fails parity
    # when its purity@10 drops more than 0.02 absolute below the classic
    # arm (analogy reported beside it; both rows land in EVAL_RUNS) ---
    ap.add_argument("--hotrow-ab", action="store_true",
                    help="train TWO arms on the identical corpus/seed — "
                         "classic per-step scatters and hot_rows=--hot-rows "
                         "— and emit one EVAL_RUNS row per arm "
                         "(hotrow_ab_arm=classic/hot) plus a parity verdict "
                         "(purity drop > 0.02 absolute fails)")
    ap.add_argument("--hot-rows", type=int, default=4096,
                    help="hot_rows for the hot arm of --hotrow-ab")
    ap.add_argument("--hot-flush-every", type=int, default=0,
                    help="hot_flush_every for the hot arm (0 = auto: once "
                         "per dispatch chunk)")
    # --- local-SGD staleness gate (ISSUE 17 / docs/sharding.md §Local-SGD):
    # sync_every=k trades k× fewer data-axis collective bytes (priced by
    # tools/collectives.py --sync-every) for k−1 steps of gradient staleness
    # per shard, so the knob ships default-off behind THIS measured A/B: two
    # shard_map arms on the identical corpus/seed over a mesh with a real
    # data axis, sync_every=1 vs sync_every=--sync-every, scored on the same
    # ladder. Documented tolerance: the local arm fails the gate when its
    # purity@10 drops more than 0.03 absolute below the synchronous arm ---
    ap.add_argument("--localsgd-ab", action="store_true",
                    help="train TWO shard_map arms on the identical "
                         "corpus/seed — sync_every=1 and "
                         "sync_every=--sync-every — on a data-parallel mesh "
                         "and emit one EVAL_RUNS row per arm "
                         "(localsgd_ab_arm=sync/local) plus a staleness "
                         "verdict (purity drop > 0.03 absolute fails)")
    ap.add_argument("--sync-every", type=int, default=8,
                    help="sync_every for the local arm of --localsgd-ab "
                         "(must divide steps_per_dispatch=32)")
    ap.add_argument("--stab-ab", action="store_true",
                    help="train TWO arms on the identical corpus/seed — the "
                         "unmitigated baseline (all stabilizers off, "
                         "norm_watch off) and the stabilized arm (the "
                         "--max-row-norm/--update-clip/--row-l2/--norm-watch "
                         "knobs; defaults to max_row_norm=100 + "
                         "norm_watch=recover when none given) — and emit one "
                         "EVAL_RUNS row per arm, so the collapse rung judges "
                         "the clamp/backoff variants on measured purity")
    args = ap.parse_args()

    if args.localsgd_ab and "jax" not in sys.modules:
        # the A/B needs a data axis; on a host with no accelerator the CPU
        # backend exposes 1 device, so self-provision the virtual 8-device
        # mesh BEFORE jax initializes (the flag is a no-op on real TPU runs)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    from glint_word2vec_tpu.data.corpus import TokenFileCorpus
    from glint_word2vec_tpu.models.estimator import Word2Vec

    lr = args.lr if args.lr is not None else 0.025

    os.makedirs(args.out, exist_ok=True)
    if args.rescore:
        emb = np.load(os.path.join(args.out, "syn0.npy"))
        with open(os.path.join(args.out, "vocab_words.txt")) as f:
            words = f.read().splitlines()
        if not any(w.startswith(("t0", "t1", "s_")) and "_w" in w
                   for w in words[:1000]):
            ap.error("--rescore needs a model trained on the synthetic ground-truth "
                     "corpus (vocab_words.txt has no t###_w##### names); external-"
                     "corpus models have no labels to score against")
        result = {"metric": "topic_recovery_at_text8_scale", "rescored": True,
                  "corpus_words": args.words, "vocab_raw": args.vocab,
                  "vocab_size": len(words), "dim": int(emb.shape[1]),
                  "iterations": args.iters, "param_dtype": args.param_dtype,
                  "logits_dtype": args.logits_dtype or "float32",
                  "pairs_per_batch": args.batch, "negative_pool": args.pool,
                  "subsample_ratio": args.subsample,
                  "device_pairgen": bool(args.device_pairgen),
                  "cbow": bool(args.cbow), "min_count": args.min_count,
                  # generator-constants provenance: gen_version alone cannot
                  # distinguish tuning iterations of the same version
                  "rel_sent_frac": REL_SENT_FRAC,
                  "rel_lambda_entity": REL_LAMBDA_ENTITY,
                  "rel_lambda_role": REL_LAMBDA_ROLE,
                  # only when given explicitly: the saved model's lr is
                  # unknowable here, and a default would fake provenance
                  **({"learning_rate": args.lr} if args.lr is not None else {})}
        result.update(evaluate(words, emb.astype(np.float32)))
        print(json.dumps(result))
        with open(os.path.join(os.path.dirname(_here), "EVAL_RUNS.jsonl"),
                  "a") as f:
            f.write(json.dumps(result) + "\n")
        return
    if args.corpus:
        corpus_path = args.corpus
    else:
        # cache key carries the tunable constants: a retune without a version
        # bump must NOT silently reuse a stale corpus while the row records the
        # new constants (the false-provenance hole the fields exist to prevent)
        gen_tag = (f"v{GEN_VERSION}-{REL_SENT_FRAC:g}-{REL_LAMBDA_ENTITY:g}"
                   f"-{REL_LAMBDA_ROLE:g}")
        corpus_path = os.path.join(
            args.out,
            f"corpus_{gen_tag}_{args.words}_{args.vocab}_{args.seed}.txt")
        if not os.path.exists(corpus_path):
            generate_corpus(corpus_path, args.words, args.seed, args.vocab)
        else:
            log(f"reusing corpus at {corpus_path}")

    sents = TokenFileCorpus(corpus_path)
    cache_dir = os.path.join(
        args.out, (f"encoded_{gen_tag}_{args.words}_{args.vocab}"
                   f"_{args.min_count}") if not args.corpus else
        f"encoded_ext_{args.words}_{args.min_count}")

    def run_arm(stab: dict, save_arrays: bool, arm: str = "",
                arm_field: str = "stab_ab_arm", plan=None):
        """Train one configuration and score it; appends the EVAL_RUNS row
        (ground-truth corpora only) carrying the requested stabilizer knobs
        AND the engaged end state, and returns the result dict. ``arm_field``
        names the A/B-arm key the row carries (stab_ab_arm / hotrow_ab_arm /
        localsgd_ab_arm), so every A/B harness funnels through this one
        trainer. ``plan`` pins the mesh (the local-SGD A/B needs a real data
        axis; every other caller takes the default)."""
        est = Word2Vec(
            vector_size=args.dim, min_count=args.min_count, window=5,
            negatives=5, negative_pool=args.pool,
            pairs_per_batch=args.batch, steps_per_dispatch=32,
            num_iterations=args.iters,
            learning_rate=lr, subsample_ratio=args.subsample, seed=args.seed,
            param_dtype=args.param_dtype,
            compute_dtype=args.param_dtype,
            logits_dtype=args.logits_dtype or "float32",
            # the EVAL suite's whole job is to MEASURE the divergence
            # boundary, so it must be allowed to train configs the trainer
            # would refuse
            allow_unstable=True,
            device_pairgen=args.device_pairgen, cbow=args.cbow, **stab)
        from glint_word2vec_tpu.train.faults import (
            NonFiniteParamsError, NormBlowupError)
        t0 = time.perf_counter()
        try:
            model = est.fit(sents, plan=plan, encode_cache_dir=cache_dir)
        except (NonFiniteParamsError, NormBlowupError) as e:
            # an unmitigated arm may halt mid-run (that IS the measurement:
            # the boundary); record the divergence as a row instead of
            # killing the other arm's result
            log(f"arm {arm or 'run'} diverged: {type(e).__name__}: "
                f"{str(e)[:160]}")
            result = {
                "metric": "topic_recovery_at_text8_scale",
                "corpus_words": args.words, "vocab_raw": args.vocab,
                "dim": args.dim, "iterations": args.iters,
                "pairs_per_batch": args.batch, "negative_pool": args.pool,
                "subsample_ratio": args.subsample, "min_count": args.min_count,
                "learning_rate": lr, "diverged": type(e).__name__,
                **stab, **({arm_field: arm} if arm else {})}
            if not args.corpus:
                with open(os.path.join(os.path.dirname(_here),
                                       "EVAL_RUNS.jsonl"), "a") as f:
                    f.write(json.dumps(result) + "\n")
            return result
        train_s = time.perf_counter() - t0
        log(f"trained{f' [{arm}]' if arm else ''}: vocab "
            f"{model.num_words:,}, d={args.dim}, {args.iters} iters "
            f"in {train_s:.0f}s (incl. vocab+encode passes)")
        if save_arrays:
            np.save(os.path.join(args.out, "syn0.npy"),
                    np.asarray(model.syn0, np.float32))
            with open(os.path.join(args.out, "vocab_words.txt"), "w") as f:
                f.write("\n".join(model.vocab.words))
        result = {
            "metric": "topic_recovery_at_text8_scale",
            "corpus_words": args.words,
            "vocab_raw": args.vocab,
            "vocab_size": model.num_words,
            "dim": args.dim,
            "iterations": args.iters,
            "train_seconds_total": round(train_s, 1),
            "param_dtype": args.param_dtype,
            "logits_dtype": args.logits_dtype or "float32",
            "pairs_per_batch": args.batch,
            "negative_pool": args.pool,
            "subsample_ratio": args.subsample,
            "device_pairgen": bool(args.device_pairgen),
            "cbow": bool(args.cbow),
            "min_count": args.min_count,
            # generator-constants provenance (rows are only comparable within
            # one constants set; gen_version alone cannot distinguish tuning
            # rounds)
            "rel_sent_frac": REL_SENT_FRAC,
            "rel_lambda_entity": REL_LAMBDA_ENTITY,
            "rel_lambda_role": REL_LAMBDA_ROLE,
            "learning_rate": lr,
            # requested stabilizer/recovery knobs + the ENGAGED end state
            # (recovery may have backed lr off / engaged the clamp mid-run)
            **stab,
            **getattr(est, "last_run_stats", {}),
            **({arm_field: arm} if arm else {}),
        }
        if not args.corpus:
            result.update(evaluate(model.vocab.words,
                                   np.asarray(model.syn0, np.float32),
                                   model.vocab.index))
            # machine-readable run log: bench.py's headline cross-check
            # refuses configs this file marks divergent or has never seen.
            # Only ground-truth (synthetic corpus) runs qualify as stability
            # evidence — external-corpus runs have no divergence metrics and
            # are not appended.
            repo_root = os.path.dirname(_here)
            with open(os.path.join(repo_root, "EVAL_RUNS.jsonl"), "a") as f:
                f.write(json.dumps(result) + "\n")
        return result

    if args.continual_ab:
        if args.corpus:
            ap.error("--continual-ab needs the synthetic ground-truth corpus "
                     "(external corpora have no labels to score forgetting "
                     "against)")
        import shutil

        from glint_word2vec_tpu.continual import ContinualRunner
        from glint_word2vec_tpu.models.word2vec import Word2VecModel

        est = Word2Vec(
            vector_size=args.dim, min_count=args.min_count, window=5,
            negatives=5, negative_pool=args.pool,
            pairs_per_batch=args.batch, steps_per_dispatch=32,
            num_iterations=args.iters, learning_rate=lr,
            subsample_ratio=args.subsample, seed=args.seed,
            param_dtype=args.param_dtype, compute_dtype=args.param_dtype,
            logits_dtype=args.logits_dtype or "float32",
            allow_unstable=True, device_pairgen=args.device_pairgen,
            cbow=args.cbow,
            continual_lr_rewarm=args.continual_lr_rewarm,
            continual_iterations=args.continual_iterations)
        t0 = time.perf_counter()
        model = est.fit(sents, encode_cache_dir=cache_dir)
        base_s = round(time.perf_counter() - t0, 1)
        words_base = list(model.vocab.words)
        index_base = dict(model.vocab.index)
        v_base = model.num_words
        log(f"continual-ab base: vocab {v_base:,} in {base_s}s")
        common = {
            "metric": "topic_recovery_at_text8_scale",
            "corpus_words": args.words, "vocab_raw": args.vocab,
            "vocab_size": v_base, "dim": args.dim,
            "iterations": args.iters, "param_dtype": args.param_dtype,
            "logits_dtype": args.logits_dtype or "float32",
            "pairs_per_batch": args.batch, "negative_pool": args.pool,
            "subsample_ratio": args.subsample, "min_count": args.min_count,
            "learning_rate": lr, "rel_sent_frac": REL_SENT_FRAC,
            "rel_lambda_entity": REL_LAMBDA_ENTITY,
            "rel_lambda_role": REL_LAMBDA_ROLE,
            "continual_tail_words": (args.continual_tail_words
                                     or args.words // 4),
            "continual_new_types": args.continual_new_types,
            "continual_lr_rewarm": args.continual_lr_rewarm,
            "continual_iterations": args.continual_iterations,
        }
        row_pre = {**common, "continual_ab_arm": "pre",
                   "train_seconds_total": base_s}
        row_pre.update(evaluate(
            words_base, np.asarray(model.syn0, np.float32), index_base))

        croot = os.path.join(args.out, "continual")
        shutil.rmtree(croot, ignore_errors=True)
        ckpath = os.path.join(croot, "publish", "ck")
        model.save(ckpath)
        stream_dir = os.path.join(croot, "stream")
        os.makedirs(stream_dir, exist_ok=True)
        # the drifted tail: extra raw types past --vocab are NEW words
        # (their names encode their topics, so ground truth still travels
        # with the corpus); Zipf over the larger support shifts every
        # surviving word's frequency too
        generate_corpus(
            os.path.join(stream_dir, "seg-001.txt"),
            common["continual_tail_words"], args.seed + 1000,
            args.vocab + args.continual_new_types)
        runner = ContinualRunner(
            ckpath, stream_dir, os.path.join(croot, "work"),
            config_overrides=dict(
                allow_unstable=True,
                continual_lr_rewarm=args.continual_lr_rewarm,
                continual_iterations=args.continual_iterations))
        inc = runner.run_once()
        runner.close()
        log(f"continual-ab increment: {inc}")
        post = Word2VecModel.load(ckpath)
        emb_post = np.asarray(post.syn0, np.float32)[:v_base]
        row_post = {**common, "continual_ab_arm": "post",
                    "continual_new_words": inc["new_words"],
                    "continual_vocab_size": inc["vocab_size"],
                    "train_seconds_total": inc["train_seconds"]}
        # scored over the ORIGINAL vocabulary's rows only — the identity-
        # prefix contract makes emb_post[:v_base] exactly those words
        row_post.update(evaluate(words_base, emb_post, index_base))
        repo_root = os.path.dirname(_here)
        with open(os.path.join(repo_root, "EVAL_RUNS.jsonl"), "a") as f:
            f.write(json.dumps(row_pre) + "\n")
            f.write(json.dumps(row_post) + "\n")
        delta = None
        if "purity_at_10" in row_pre and "purity_at_10" in row_post:
            delta = round(row_post["purity_at_10"] - row_pre["purity_at_10"],
                          4)
        print(json.dumps({
            "metric": "continual_ab", "purity_delta": delta,
            "purity_pre": row_pre.get("purity_at_10"),
            "purity_post": row_post.get("purity_at_10"),
            "analogy_pre": row_pre.get("analogy_accuracy_at_1"),
            "analogy_post": row_post.get("analogy_accuracy_at_1"),
            "vocab_base": v_base, "vocab_grown": inc["vocab_size"],
            "new_words": inc["new_words"],
            "arms": [row_pre, row_post]}))
        return

    if args.hotrow_ab:
        # the ISSUE-14 parity gate: classic per-step scatters vs hot-row
        # accumulation, identical corpus/seed, scored on the same ladder.
        # Documented tolerance: hot-arm purity@10 more than 0.02 absolute
        # below the classic arm fails parity (the knob then stays off).
        r_classic = run_arm(dict(hot_rows=0), save_arrays=False,
                            arm="classic", arm_field="hotrow_ab_arm")
        r_hot = run_arm(
            dict(hot_rows=args.hot_rows,
                 hot_flush_every=args.hot_flush_every),
            save_arrays=True, arm="hot", arm_field="hotrow_ab_arm")
        delta = analogy_delta = None
        if "purity_at_10" in r_classic and "purity_at_10" in r_hot:
            delta = round(r_hot["purity_at_10"] - r_classic["purity_at_10"],
                          4)
        if ("analogy_accuracy_at_1" in r_classic
                and "analogy_accuracy_at_1" in r_hot):
            analogy_delta = round(r_hot["analogy_accuracy_at_1"]
                                  - r_classic["analogy_accuracy_at_1"], 4)
        print(json.dumps({
            "metric": "hotrow_ab",
            "hot_rows": args.hot_rows,
            "hot_flush_every": args.hot_flush_every,
            "purity_delta": delta,
            "analogy_delta": analogy_delta,
            "parity_ok": (delta is not None and delta >= -0.02),
            "parity_rule": "hot purity_at_10 >= classic - 0.02 absolute",
            "arms": [r_classic, r_hot]}))
        return

    if args.localsgd_ab:
        # the ISSUE-17 staleness-vs-throughput gate: synchronous shard_map vs
        # the sync_every=k owner-local window, identical corpus/seed/mesh,
        # scored on the same ladder. Documented tolerance: local-arm
        # purity@10 more than 0.03 absolute below the sync arm fails the
        # gate (the knob then stays at 1 for that geometry).
        import jax

        from glint_word2vec_tpu.parallel.mesh import make_mesh
        if args.sync_every <= 1 or 32 % args.sync_every:
            ap.error("--sync-every must be > 1 and divide "
                     f"steps_per_dispatch=32 (got {args.sync_every})")
        n_dev = len(jax.devices())
        if n_dev < 2:
            ap.error("--localsgd-ab needs >= 2 devices for a data axis "
                     f"(have {n_dev})")
        # widest data axis the device count allows, capped at 2 shards of
        # model parallelism — matches the headline 2x4 pricing geometry on 8
        # devices while still degrading to 2x1 on a 2-device host
        plan = make_mesh(max(2, n_dev // 4))
        log(f"localsgd-ab mesh: {plan.num_data}x{plan.num_model}, "
            f"sync_every={args.sync_every}")
        r_sync = run_arm(dict(step_lowering="shard_map", sync_every=1),
                         save_arrays=False, arm="sync",
                         arm_field="localsgd_ab_arm", plan=plan)
        r_local = run_arm(dict(step_lowering="shard_map",
                               sync_every=args.sync_every),
                          save_arrays=True, arm="local",
                          arm_field="localsgd_ab_arm", plan=plan)
        delta = analogy_delta = None
        if "purity_at_10" in r_sync and "purity_at_10" in r_local:
            delta = round(r_local["purity_at_10"] - r_sync["purity_at_10"], 4)
        if ("analogy_accuracy_at_1" in r_sync
                and "analogy_accuracy_at_1" in r_local):
            analogy_delta = round(r_local["analogy_accuracy_at_1"]
                                  - r_sync["analogy_accuracy_at_1"], 4)
        print(json.dumps({
            "metric": "localsgd_ab",
            "sync_every": args.sync_every,
            "mesh": [plan.num_data, plan.num_model],
            "purity_delta": delta,
            "analogy_delta": analogy_delta,
            "staleness_ok": (delta is not None and delta >= -0.03),
            "staleness_rule": "local purity_at_10 >= sync - 0.03 absolute",
            "arms": [r_sync, r_local]}))
        return

    stab = dict(max_row_norm=args.max_row_norm, update_clip=args.update_clip,
                row_l2=args.row_l2, norm_watch=args.norm_watch)
    if args.stab_ab:
        if not (args.max_row_norm or args.update_clip or args.row_l2
                or args.norm_watch != "off"):
            # the default stabilized arm: the clamp at the watchdog-threshold
            # provenance value + the full recovery ladder
            stab = dict(max_row_norm=100.0, update_clip=0.0, row_l2=0.0,
                        norm_watch="recover")
        # the unmitigated arm is UNMITIGATED: no stabilizers, no watchdog,
        # and no non-finite guardrail either (nonfinite_policy="none", the
        # round-5 measurement posture) — a run that NaNs still trains to the
        # end and scores, with the non-finite rows masked out of purity and
        # counted in rows_inf, so the A/B always compares purity to purity
        off = dict(max_row_norm=0.0, update_clip=0.0, row_l2=0.0,
                   norm_watch="off", nonfinite_policy="none")
        r_off = run_arm(off, save_arrays=False, arm="unmitigated")
        r_stab = run_arm(stab, save_arrays=True, arm="stabilized")
        delta = None
        if "purity_at_10" in r_off and "purity_at_10" in r_stab:
            delta = round(r_stab["purity_at_10"] - r_off["purity_at_10"], 4)
        print(json.dumps({"metric": "stabilizer_ab",
                          "purity_delta": delta,
                          "arms": [r_off, r_stab]}))
        return
    print(json.dumps(run_arm(stab, save_arrays=True)))


if __name__ == "__main__":
    main()
