"""R3 transitive-closure bad fixture: the host syncs live in a HELPER the
jitted function calls by name (the obs/probe.py shape — `_matrix_stats` runs
inside the fused probe but is not itself a jit target). Pre-closure R3 never
walked it."""

import time

import jax
import jax.numpy as jnp


def _stats_helper(m):
    scale = float(m.sum())            # concretizes a tracer
    t = time.perf_counter()           # host clock baked in at trace time
    return jnp.max(m) * scale + t


def make_probe():
    def probe(params):
        return _stats_helper(params)

    return jax.jit(probe)
