"""Request batching scheduler: a bounded queue + deadline micro-batcher.

The serving economics this exists for (PERF.md §6, ROADMAP item 1): one
exact synonym query at V=1M costs 230-375 ms through a thin host→device
link, but 64 queries coalesced into ONE device dispatch cost 13-16 ms
total — the per-query round trip dominates, not the math. This scheduler
turns N concurrent callers into that one dispatch:

- ``submit()`` enqueues a request and blocks the calling thread until its
  result is ready (clients are threads — the stdin CLI, the bench harness's
  closed-loop clients, the chaos storm);
- one worker thread pops the queue and coalesces up to ``max_batch``
  requests, waiting at most ``max_delay_ms`` past the FIRST request's
  arrival (latency is bounded by the deadline, throughput by the batch cap);
- the whole batch goes to the ``handler`` callable in one call; the handler
  returns one result per request (an ``Exception`` instance marks a
  per-request failure — an OOV word must fail ITS caller, not the batch);
- **backpressure is a fast refusal, never unbounded memory**: a full queue
  raises :class:`ServerOverloaded` to the caller immediately (the 429-style
  contract) instead of queueing into latency collapse.

Determinism note (graftlint R1): the worker thread is a sanctioned owner —
it only ORDERS request/response pairing (each caller gets exactly its own
result back) and is read-only on model parameters; it never produces or
orders training data, so the worker-count determinism contract is
untouched. Batch COMPOSITION is timing-dependent by design (that is what a
micro-batcher is); per-request results are not, because the handler maps
item i to result i.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence
from glint_word2vec_tpu.lockcheck import make_condition

logger = logging.getLogger("glint_word2vec_tpu")


class ServerOverloaded(RuntimeError):
    """Admission refused: the bounded queue is full. The serving analog of
    HTTP 429 — callers should shed or retry with backoff; the server never
    buffers unboundedly.

    ``retry_after_s`` is the machine-readable backoff hint (the Retry-After
    header analog): queued batches ahead × the observed batch service time —
    how long the present backlog takes to drain at the measured rate. None
    when the server has not yet completed a batch to measure. Fleet routers
    honor it as "retry ELSEWHERE now, retry HERE after the hint"
    (serve/fleet.py)."""

    status = 429

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ServiceClosed(RuntimeError):
    """Submit refused: the scheduler is stopping or stopped. The typed
    shutdown refusal — before this class a submit racing ``stop()`` got
    whatever the dead worker queue produced (a bare RuntimeError at best, a
    forever-parked ticket at worst). Distinct from :class:`ServerOverloaded`
    on purpose: overload means "retry later / elsewhere", closed means "this
    replica is going away — re-resolve, don't retry here"."""


class _Ticket:
    """One in-flight request: payload in, result/error out, an event the
    submitting thread parks on. ``trace`` is the cross-process trace
    context (``{"tid": ..., "ps": ...}``, obs/trace.py) when the request is
    being traced, else None — the default path allocates nothing extra."""

    __slots__ = ("payload", "enqueued", "done", "result", "error", "trace")

    def __init__(self, payload: Any, trace: Optional[dict] = None):
        self.payload = payload
        self.enqueued = time.monotonic()
        self.done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.trace = trace


class BatchingScheduler:
    """Deadline-based micro-batcher over a bounded queue (module doc)."""

    def __init__(
        self,
        handler: Callable[[List[Any]], Sequence[Any]],
        max_batch: int = 64,
        max_delay_ms: float = 2.0,
        max_queue: int = 256,
        name: str = "glint-serve-batcher",
        straggle_every: int = 0,
        straggle_ms: float = 0.0,
        span_emit: Optional[Callable[[dict, str, int, int], None]] = None,
        batch_observer: Optional[Callable[[int, float, float], None]] = None,
    ):
        """``straggle_every``/``straggle_ms`` are FAULT INJECTION (the
        serve-side analog of train/faults.py, off by default): every Nth
        dispatched batch sleeps ``straggle_ms`` before the handler runs — a
        deterministic tail-latency straggler. The fleet hedge A/B
        (tools/servebench.py --fleet) uses it to measure what hedging buys
        against a replica that stalls 1-in-N dispatches; production never
        sets it.

        ``span_emit(trace, name, start_mono_ns, dur_ns)``: the trace hook
        (obs/trace.py) the worker calls per TRACED ticket after each batch —
        a ``queue_wait`` span (submit → batch pop: the admission latency the
        micro-batching deadline trades) and a ``batch_service`` span (the
        handler's wall time), both parented to the context the request
        carried across the wire. Untraced tickets (trace=None — every
        ticket when tracing is off) never reach the hook: the zero-cost
        contract is "no trace, no call", not a no-op callee.

        ``batch_observer(batch_size, service_s, queue_wait_s)``: called once
        per dispatched batch (success or error) — the serving flight
        recorder's dispatch-ring feed (obs/blackbox.py note_dispatch via
        EmbeddingService). Both hooks run ON the worker thread; they must
        not block (the sink's locked append is the intended cost)."""
        if max_batch <= 0:
            raise ValueError(f"max_batch must be positive but got {max_batch}")
        if max_delay_ms < 0:
            raise ValueError(
                f"max_delay_ms must be nonnegative but got {max_delay_ms}")
        if max_queue <= 0:
            raise ValueError(f"max_queue must be positive but got {max_queue}")
        self._handler = handler
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.max_queue = int(max_queue)
        self._straggle_every = int(straggle_every)
        self._straggle_s = float(straggle_ms) / 1000.0
        self._span_emit = span_emit
        self._batch_observer = batch_observer
        self._name = name
        self._q: collections.deque = collections.deque()
        self._cv = make_condition("serve.batcher.cv")
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        # counters (all mutated under _cv)
        self._submitted = 0
        self._refused = 0
        self._completed = 0
        self._errors = 0
        self._batches = 0
        self._batched_items = 0
        # recent end-to-end latencies (seconds); deque append is atomic, so
        # submitters record lock-free and stats() snapshots a copy
        self._latencies: collections.deque = collections.deque(maxlen=4096)
        # EWMA of the handler's per-batch wall time (seconds), updated by
        # the worker after every dispatch — feeds the ServerOverloaded
        # retry_after_s hint. None until the first batch completes.
        self._batch_s_ewma: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------------------

    def start(self) -> "BatchingScheduler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name=self._name, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> int:
        """Drain-and-stop: requests already admitted are still served (the
        worker keeps batching until the queue is empty), new submits are
        refused. Returns the number of leaked threads (1 when the worker
        misses the join bound) so close() paths can surface it in stats."""
        with self._cv:
            if self._stopping:
                return 0
            self._stopping = True
            self._cv.notify_all()
        leaked = 0
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30)
            if t.is_alive():
                leaked = 1
                logger.warning("batcher worker thread leaked (join timeout)")
        return leaked

    # -- client side -------------------------------------------------------------------

    def submit_async(self, payload: Any,
                     trace: Optional[dict] = None) -> _Ticket:
        """Enqueue one request; returns the ticket to :meth:`wait` on.
        Raises :class:`ServiceClosed` once ``stop()`` has been called (during
        the drain AND after it) and :class:`ServerOverloaded` (with the
        ``retry_after_s`` drain-time hint) when the bounded queue is full.
        ``trace`` is the optional cross-process trace context the worker
        turns into queue_wait/batch_service spans (constructor docstring)."""
        with self._cv:
            if self._stopping:
                raise ServiceClosed(
                    "scheduler is stopped — admitted requests drain, new "
                    "submits are refused")
            if len(self._q) >= self.max_queue:
                self._refused += 1
                raise ServerOverloaded(
                    f"admission queue full ({self.max_queue} waiting)",
                    retry_after_s=self._retry_after_locked())
            t = _Ticket(payload, trace)
            self._q.append(t)
            self._submitted += 1
            self._cv.notify_all()
        return t

    def wait(self, ticket: _Ticket, timeout: float = 60.0) -> Any:
        """Block until the ticket's batch completed; re-raise its per-request
        error in the caller's thread."""
        if not ticket.done.wait(timeout):
            raise TimeoutError(f"request not served within {timeout:g}s")
        # under _cv like every other ring access: a lock-free append races
        # stats()'s iteration — deque.append is atomic, but iterating a
        # deque another thread appends to raises RuntimeError (the PR 12
        # class; graftlint R11 holds every access to the same lock)
        with self._cv:
            self._latencies.append(time.monotonic() - ticket.enqueued)
        if ticket.error is not None:
            raise ticket.error
        return ticket.result

    def submit(self, payload: Any, timeout: float = 60.0) -> Any:
        """Blocking submit: enqueue + wait (the one-call client surface)."""
        return self.wait(self.submit_async(payload), timeout)

    def _retry_after_locked(self) -> Optional[float]:
        """The drain-time estimate behind ``retry_after_s`` (called under
        ``_cv``): full batches queued ahead × the EWMA batch service time.
        None before the first completed batch — an honest "no data yet"
        beats a made-up constant."""
        if self._batch_s_ewma is None:
            return None
        batches_ahead = -(-len(self._q) // self.max_batch)  # ceil
        return round(max(1, batches_ahead) * self._batch_s_ewma, 4)

    # -- worker side -------------------------------------------------------------------

    def _collect(self) -> Optional[List[_Ticket]]:
        """Pop one batch: block for the first request, then coalesce until
        ``max_batch`` or ``max_delay_ms`` past the first arrival. None =
        stopped and drained."""
        with self._cv:
            while not self._q and not self._stopping:
                self._cv.wait()
            if not self._q:
                return None  # stopping, queue drained
            batch = [self._q.popleft()]
            deadline = batch[0].enqueued + self.max_delay_s
            while len(batch) < self.max_batch:
                if self._q:
                    batch.append(self._q.popleft())
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stopping:
                    break
                self._cv.wait(remaining)
            return batch

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            pop = time.monotonic()
            if self._straggle_every:
                with self._cv:
                    nth = self._batches + 1
                if nth % self._straggle_every == 0:
                    time.sleep(self._straggle_s)  # injected straggler
            t0 = time.monotonic()
            try:
                results = self._handler([t.payload for t in batch])
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"handler returned {len(results)} results for a "
                        f"batch of {len(batch)}")
            except Exception as e:  # noqa: BLE001 — delivered to each caller
                with self._cv:
                    self._note_batch_seconds(time.monotonic() - t0)
                    self._batches += 1
                    self._batched_items += len(batch)
                    self._errors += len(batch)
                for t in batch:
                    t.error = e
                    t.done.set()
                self._after_batch(batch, pop, time.monotonic())
                continue
            n_err = 0
            for t, r in zip(batch, results):
                if isinstance(r, BaseException):
                    t.error = r
                    n_err += 1
                else:
                    t.result = r
            with self._cv:
                self._note_batch_seconds(time.monotonic() - t0)
                self._batches += 1
                self._batched_items += len(batch)
                self._errors += n_err
                self._completed += len(batch) - n_err
            for t in batch:
                t.done.set()
            self._after_batch(batch, pop, time.monotonic())

    def _after_batch(self, batch: List[_Ticket], pop_s: float,
                     done_s: float) -> None:
        """Post-batch observability (worker thread, AFTER the callers were
        released — a slow sink must not sit inside any caller's latency):
        the per-batch dispatch observer, then queue_wait/batch_service
        spans for each TRACED ticket. Best-effort like every obs surface —
        a hook failure must never kill the worker."""
        if self._batch_observer is None and self._span_emit is None:
            return
        try:
            if self._batch_observer is not None:
                self._batch_observer(
                    len(batch), done_s - pop_s,
                    max(0.0, pop_s - batch[0].enqueued))
            if self._span_emit is not None:
                pop_ns = int(pop_s * 1e9)
                dur_ns = int((done_s - pop_s) * 1e9)
                for t in batch:
                    if t.trace is None:
                        continue
                    enq_ns = int(t.enqueued * 1e9)
                    self._span_emit(t.trace, "queue_wait", enq_ns,
                                    max(0, pop_ns - enq_ns))
                    self._span_emit(t.trace, "batch_service", pop_ns, dur_ns)
        except Exception:  # noqa: BLE001 — observability is best-effort
            logger.warning("batcher trace/observer hook failed",
                           exc_info=True)

    def _note_batch_seconds(self, dt: float) -> None:
        """Fold one batch's handler wall time into the EWMA (under _cv).
        alpha=0.2: ~10 batches of memory — reactive enough that a reload's
        cold first dispatch doesn't poison the hint for long."""
        self._batch_s_ewma = (dt if self._batch_s_ewma is None
                              else 0.8 * self._batch_s_ewma + 0.2 * dt)

    # -- observability -----------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Gauge snapshot: counters, queue depth, mean batch occupancy, and
        p50/p95/p99 end-to-end latency over the recent-latency ring."""
        with self._cv:
            snap = {
                "submitted": self._submitted,
                "refused": self._refused,
                "completed": self._completed,
                "errors": self._errors,
                "batches": self._batches,
                "queue_depth": len(self._q),
                "max_batch": self.max_batch,
                "max_queue": self.max_queue,
                "occupancy_mean": (round(self._batched_items / self._batches, 3)
                                   if self._batches else None),
                "batch_service_s": (round(self._batch_s_ewma, 5)
                                    if self._batch_s_ewma is not None
                                    else None),
            }
            lats = list(self._latencies)  # snapshot under _cv; sort outside
        lats.sort()
        if lats:
            def pct(p: float) -> float:
                return round(
                    lats[min(len(lats) - 1, int(p * len(lats)))] * 1000, 3)
            snap["latency_ms"] = {"p50": pct(0.50), "p95": pct(0.95),
                                  "p99": pct(0.99), "n": len(lats)}
        return snap
