"""Scatter-add cost model: is XLA TPU scatter row-issue-bound or byte-bound?

If cost scales with the number of update rows but not with row bytes (D), the
HBM-roofline framing ("93 GB/s of 819 GB/s") is invalid — the step's floor is
rows x ns/row, and only reducing scattered rows (or finding a denser op) helps.

Measures mat.at[idx].add(upd) for a D sweep at fixed B and a B sweep at fixed D,
Zipf indices, f32 + bf16, with interleaved slope repeats (median reported).
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

V, K = 200_000, 16


def main() -> None:
    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    print(f"device: {jax.devices()[0]}", file=sys.stderr)
    rng = np.random.default_rng(0)
    c = np.maximum(1e9 / (np.arange(V) + 10.0) ** 1.07, 5.0)
    p = c / c.sum()

    def measure(b, d, dt, repeats=3):
        mat0 = jnp.asarray(rng.normal(0, 0.05, (V, d)), dt)
        upd0 = jnp.asarray(rng.normal(0, 1e-4, (b, d)), dt)
        idx = jnp.asarray(np.stack(
            [np.random.default_rng(100 + j).choice(V, size=b, p=p)
             for j in range(K)]), jnp.int32)

        def chunk(m, u, idxs):
            def body(cc, ix):
                return cc.at[ix].add(u), ()
            out, _ = jax.lax.scan(body, m, idxs)
            return out, out[0, 0]

        f = jax.jit(chunk, donate_argnums=(0,))
        ts = []
        for _ in range(repeats):
            spc = time_chunked(f, lambda: mat0 + 0, lambda i: (upd0, idx),
                               n_lo=2, n_hi=8, fetch=lambda cc, o: o)
            ts.append(spc / K * 1e3)
        return float(np.median(ts))

    for dt_name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        print(f"\n-- D sweep at B=65536 [{dt_name}] --", file=sys.stderr)
        for d in (64, 128, 384, 768):
            ms = measure(65536, d, dt)
            print(f"  D={d:4d}: {ms:7.3f} ms  ({ms * 1e6 / 65536:6.1f} ns/row, "
                  f"{2 * 65536 * d * (4 if dt_name == 'f32' else 2) / (ms / 1e3) / 1e9:6.1f} GB/s)",
                  file=sys.stderr)
        print(f"-- B sweep at D=384 [{dt_name}] --", file=sys.stderr)
        for b in (8192, 32768, 65536, 131072):
            ms = measure(b, 384, dt)
            print(f"  B={b:6d}: {ms:7.3f} ms  ({ms * 1e6 / b:6.1f} ns/row)",
                  file=sys.stderr)


if __name__ == "__main__":
    main()
